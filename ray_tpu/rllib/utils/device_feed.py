"""Double-buffered host-to-HBM batch feed for TPU learners.

reference parity: SURVEY.md §7.3 names "EnvRunner→Learner throughput"
a hard part — trajectories arrive host-side and the device feed must be
pipelined to keep env-steps/sec/chip up. The reference keeps its GPU fed
with torch pinned-memory prefetch inside the learner; the TPU-native
equivalent stages each batch into reusable pinned host buffers (one
contiguous segment per dtype — HostStage), ships the few segments with
fused `jax.device_put` calls on a feeder thread while the chip executes
update k, and carves the per-column leaves back out ON DEVICE with a
jitted, buffer-donating unfuse (the segment's HBM is reused for the
leaves instead of living twice). Residual blocking time is accounted so
benchmarks report an honest feed-stall %, and the copied-bytes counter +
transfer-time histogram (`ray_tpu_transport_*`) make
`feed_xfer_stall_pct` attributable.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu._private import spans as _spans


def _feed_metrics():
    from ray_tpu.util.metrics import Counter, Histogram, get_or_create
    counter = get_or_create(
        Counter, "ray_tpu_transport_feed_bytes_total",
        description="host->device bytes shipped by DeviceFeed")
    hist = get_or_create(
        Histogram, "ray_tpu_transport_feed_xfer_seconds",
        description="host->device transfer time per batch (seconds)",
        boundaries=[0.0005, 0.002, 0.01, 0.05, 0.2, 1.0, 5.0])
    return counter, hist


class StagedBatch:
    """One train batch packed into per-dtype contiguous host segments.

    `segments` maps dtype name -> 1-D numpy buffer holding every column
    of that dtype back to back; `layout` maps column key ->
    (dtype_name, offset_elems, n_elems, shape). The feed ships the
    segments (a handful of transfers regardless of column count) and
    reconstructs the columns on device; host-side consumers (sync path,
    gang learners) use as_dict().
    """

    __slots__ = ("segments", "layout", "_release_cb")

    def __init__(self, segments: Dict[str, np.ndarray],
                 layout: Dict[str, Tuple[str, int, int, Tuple[int, ...]]],
                 release_cb=None):
        self.segments = segments
        self.layout = layout
        self._release_cb = release_cb

    @property
    def nbytes(self) -> int:
        return sum(s.nbytes for s in self.segments.values())

    def as_dict(self) -> Dict[str, np.ndarray]:
        """Host-side column views into the staging segments (valid until
        release())."""
        return {k: self.segments[dt][off:off + n].reshape(shape)
                for k, (dt, off, n, shape) in self.layout.items()}

    def release(self) -> None:
        """Hand the staging slot back to its HostStage for reuse. Call
        only when the segments' contents are no longer referenced (the
        transfer landed, or the dict was deep-copied)."""
        cb, self._release_cb = self._release_cb, None
        if cb is not None:
            cb(self.segments)


class HostStage:
    """Pool of reusable per-dtype staging buffers.

    assemble() copies a list of same-structure fragments into ONE
    contiguous buffer per dtype — the copy that np.concatenate would do
    anyway, but into preallocated memory that is reused batch after
    batch (steady state: zero allocations on the trajectory hot path).
    Slots cycle through a bounded free list; if consumers fall behind
    the pool grows a fresh slot rather than deadlocking.
    """

    def __init__(self, slots: int = 4):
        self._slots = max(1, slots)
        self._free: "queue.Queue[Dict[str, np.ndarray]]" = queue.Queue()
        for _ in range(self._slots):
            self._free.put({})
        self.bytes_staged = 0

    def _acquire(self) -> Dict[str, np.ndarray]:
        try:
            return self._free.get_nowait()
        except queue.Empty:
            # all slots in flight (consumer stalled): grow immediately
            # rather than blocking the trajectory assembly hot path
            return {}

    def _release(self, segments: Dict[str, np.ndarray]) -> None:
        # drop oversized pools silently (the grown slot replaces a lost one)
        if self._free.qsize() < self._slots:
            self._free.put(segments)

    def assemble(self, frags: Sequence[Dict[str, np.ndarray]],
                 axis_for) -> StagedBatch:
        """Stack same-structure fragments along axis_for(key) into a
        StagedBatch backed by a pooled slot."""
        with _spans.span("feed.stage", nfrags=len(frags)) as _sp:
            sb = self._assemble_impl(frags, axis_for)
            _sp["bytes"] = sb.nbytes
            return sb

    def _assemble_impl(self, frags: Sequence[Dict[str, np.ndarray]],
                       axis_for) -> StagedBatch:
        keys = list(frags[0].keys())
        plans: List[Tuple[str, str, int, Tuple[int, ...], int]] = []
        totals: Dict[str, int] = {}
        for k in keys:
            axis = axis_for(k)
            parts = [np.asarray(f[k]) for f in frags]
            shape = list(parts[0].shape)
            shape[axis] = sum(p.shape[axis] for p in parts)
            n = int(np.prod(shape))
            dt = parts[0].dtype.name
            plans.append((k, dt, totals.get(dt, 0), tuple(shape), axis))
            totals[dt] = totals.get(dt, 0) + n
        slot = self._acquire()
        try:
            segments: Dict[str, np.ndarray] = {}
            for dt, n in totals.items():
                buf = slot.get(dt)
                if buf is None or buf.size < n:
                    buf = np.empty(max(n, 1), dtype=np.dtype(dt))
                segments[dt] = buf
            layout: Dict[str, Tuple[str, int, int, Tuple[int, ...]]] = {}
            for k, dt, off, shape, axis in plans:
                n = int(np.prod(shape))
                dest = segments[dt][off:off + n].reshape(shape)
                parts = [np.asarray(f[k]) for f in frags]
                if len(parts) == 1:
                    np.copyto(dest, parts[0])
                else:
                    np.concatenate(parts, axis=axis, out=dest)
                layout[k] = (dt, off, n, shape)
                self.bytes_staged += dest.nbytes
        except BaseException:
            # the StagedBatch below takes slot ownership; until then a
            # failed assembly (mismatched frag shape/dtype) must hand
            # the slot back or the stage permanently loses capacity
            self._release(slot)
            raise
        return StagedBatch(segments, layout, release_cb=self._release)


class DeviceFeed:
    """Pulls (batch, meta) items from a host queue, eagerly dispatches
    the host→device transfer, and hands device-resident batches to the
    consumer.

    `depth` bounds how many transfers may be in flight (double buffering
    at the default 2): enough to hide transfer latency behind compute,
    small enough not to pile batches up in HBM.

    StagedBatch items take the fused path: one device_put per dtype
    segment (instead of one per column), an on-device jitted unfuse that
    DONATES the segment buffers into the reconstructed columns, and slot
    recycling back to the HostStage the moment the transfer lands.

    Stall accounting (all in seconds, monotonically increasing):
      - wait_s: total consumer time blocked in get() — includes upstream
        sample starvation, i.e. the true EnvRunner→Learner gap.
      - xfer_s: the part of wait_s spent waiting for an already-dequeued
        transfer to land in HBM (pure host→device feed stall).
      - busy_s: consumer-reported compute time (add via add_busy).
    """

    def __init__(self, host_queue: "queue.Queue",
                 depth: int = 2,
                 stop_event: Optional[threading.Event] = None,
                 stall_bucket: str = "feed_stall"):
        self._host = host_queue
        # goodput bucket the consumer's blocked get() time charges to
        # (replay learners pass "replay_stall")
        self._stall_bucket = stall_bucket
        self._out: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._stop = stop_event or threading.Event()
        self.wait_s = 0.0
        self.xfer_s = 0.0
        self.busy_s = 0.0
        self.batches = 0
        self.fused_batches = 0
        self.bytes_fed = 0
        self._unfuse_cache: Dict[Tuple, Any] = {}
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="device-feed")
        self._thread.start()

    # -- fused transfer ------------------------------------------------

    def _unfuse_fn(self, layout_sig: Tuple):
        """Jitted segments->columns reconstruction for one layout. The
        segment arrays are donated: XLA reuses their HBM for the column
        views instead of keeping batch bytes resident twice."""
        import jax
        fn = self._unfuse_cache.get(layout_sig)
        if fn is None:
            layout = {k: (dt, off, n, shape)
                      for k, dt, off, n, shape in layout_sig}

            def unfuse(segs):
                return {k: jax.lax.dynamic_slice_in_dim(
                            segs[dt], off, n).reshape(shape)
                        for k, (dt, off, n, shape)
                        in sorted(layout.items())}

            donate = () if jax.default_backend() == "cpu" else (0,)
            fn = jax.jit(unfuse, donate_argnums=donate)
            self._unfuse_cache[layout_sig] = fn
        return fn

    def _ship(self, batch: Any) -> Tuple[Any, int]:
        """Host→device for one batch; returns (device batch, bytes)."""
        import jax
        if isinstance(batch, StagedBatch):
            nbytes = batch.nbytes
            try:
                with _spans.span("feed.ship", bytes=nbytes, fused=True):
                    segs = {dt: jax.device_put(seg)
                            for dt, seg in sorted(batch.segments.items())}
                    # intentional barrier: the transfer must land before
                    # the slot is reused # graftlint: disable=RT021
                    jax.block_until_ready(list(segs.values()))
                sig = tuple((k, dt, off, n, shape)
                            for k, (dt, off, n, shape)
                            in sorted(batch.layout.items()))
                with _spans.span("feed.unfuse"):
                    dev = self._unfuse_fn(sig)(segs)
            finally:
                # a failed device_put/unfuse must still return the slot
                # to the stage, or the feed wedges once slots run out
                batch.release()
            self.fused_batches += 1
            return dev, nbytes
        with _spans.span("feed.ship", fused=False) as _sp:
            dev = jax.device_put(batch)
            # intentional barrier: ship measures landed-transfer time,
            # and nbytes reads need materialized leaves
            jax.block_until_ready(dev)  # graftlint: disable=RT021
            nbytes = sum(getattr(v, "nbytes", 0)
                         for v in jax.tree_util.tree_leaves(dev))
            _sp["bytes"] = nbytes
        return dev, nbytes

    def _run(self) -> None:
        counter = hist = None
        while not self._stop.is_set():
            try:
                batch, meta = self._host.get(timeout=0.2)
            except queue.Empty:
                continue
            t0 = time.perf_counter()
            dev, nbytes = self._ship(batch)
            dt = time.perf_counter() - t0
            self.bytes_fed += nbytes
            if counter is None:
                try:
                    counter, hist = _feed_metrics()
                except Exception:  # noqa: BLE001 - metrics best-effort
                    counter, hist = False, False
            if counter:
                counter.inc(nbytes)
                hist.observe(dt)
            while not self._stop.is_set():
                try:
                    self._out.put((dev, meta), timeout=0.2)
                    break
                except queue.Full:
                    continue

    def get(self, timeout: float = 0.2) -> Tuple[Any, Any]:
        """Next device-resident batch; raises queue.Empty on timeout.
        Blocks until the transfer has actually landed so downstream
        compute timing is clean. Starvation (nothing queued — the
        upstream sampler is the bottleneck) and transfer wait both
        accumulate into wait_s; xfer_s isolates the transfer part."""
        import jax
        t0 = time.perf_counter()
        # feed.wait = consumer blocked on the feed (starvation: upstream
        # sampling is the bottleneck); feed.xfer isolates the tail spent
        # waiting for an already-dequeued transfer to land in HBM
        from ray_tpu._private import goodput
        with _spans.span("feed.wait") as _sp:
            try:
                dev, meta = self._out.get(timeout=timeout)
            except queue.Empty:
                waited = time.perf_counter() - t0
                self.wait_s += waited
                # starvation is badput on the consumer's ledger even
                # when the get comes back empty
                goodput.charge(self._stall_bucket, waited)
                _sp["empty"] = True
                raise
            t1 = time.perf_counter()
            with _spans.span("feed.xfer"):
                # intentional barrier: xfer_s attributes residual
                # transfer time to the consumer-visible wait
                jax.block_until_ready(dev)  # graftlint: disable=RT021
            t2 = time.perf_counter()
        self.wait_s += t2 - t0
        self.xfer_s += t2 - t1
        goodput.charge(self._stall_bucket, t2 - t0)
        self.batches += 1
        return dev, meta

    def add_busy(self, seconds: float) -> None:
        self.busy_s += seconds

    def stats(self) -> dict:
        total = self.wait_s + self.busy_s
        return {
            "feed_wait_s": self.wait_s,
            "feed_xfer_s": self.xfer_s,
            "learner_busy_s": self.busy_s,
            "feed_stall_pct": (100.0 * self.wait_s / total) if total else 0.0,
            "feed_xfer_stall_pct": (
                100.0 * self.xfer_s / total) if total else 0.0,
            "batches_fed": self.batches,
            "fused_batches": self.fused_batches,
            "feed_bytes": self.bytes_fed,
        }

    def stop(self) -> None:
        self._stop.set()
