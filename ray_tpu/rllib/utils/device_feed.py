"""Double-buffered host-to-HBM batch feed for TPU learners.

reference parity: SURVEY.md §7.3 names "EnvRunner→Learner throughput"
a hard part — trajectories arrive host-side and the device feed must be
pipelined to keep env-steps/sec/chip up. The reference keeps its GPU fed
with torch pinned-memory prefetch inside the learner; the TPU-native
equivalent dispatches `jax.device_put` for batch k+1 on a feeder thread
while the chip executes update k, and accounts residual blocking time so
benchmarks can report an honest feed-stall %.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Optional, Tuple


class DeviceFeed:
    """Pulls (batch, meta) items from a host queue, eagerly dispatches
    the host→device transfer, and hands device-resident batches to the
    consumer.

    `depth` bounds how many transfers may be in flight (double buffering
    at the default 2): enough to hide transfer latency behind compute,
    small enough not to pile batches up in HBM.

    Stall accounting (all in seconds, monotonically increasing):
      - wait_s: total consumer time blocked in get() — includes upstream
        sample starvation, i.e. the true EnvRunner→Learner gap.
      - xfer_s: the part of wait_s spent waiting for an already-dequeued
        transfer to land in HBM (pure host→device feed stall).
      - busy_s: consumer-reported compute time (add via add_busy).
    """

    def __init__(self, host_queue: "queue.Queue",
                 depth: int = 2,
                 stop_event: Optional[threading.Event] = None):
        self._host = host_queue
        self._out: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._stop = stop_event or threading.Event()
        self.wait_s = 0.0
        self.xfer_s = 0.0
        self.busy_s = 0.0
        self.batches = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="device-feed")
        self._thread.start()

    def _run(self) -> None:
        import jax
        while not self._stop.is_set():
            try:
                batch, meta = self._host.get(timeout=0.2)
            except queue.Empty:
                continue
            # Async dispatch: returns immediately; the copy streams to the
            # device while the consumer is still computing on batch k-1.
            dev = jax.device_put(batch)
            while not self._stop.is_set():
                try:
                    self._out.put((dev, meta), timeout=0.2)
                    break
                except queue.Full:
                    continue

    def get(self, timeout: float = 0.2) -> Tuple[Any, Any]:
        """Next device-resident batch; raises queue.Empty on timeout.
        Blocks until the transfer has actually landed so downstream
        compute timing is clean. Starvation (nothing queued — the
        upstream sampler is the bottleneck) and transfer wait both
        accumulate into wait_s; xfer_s isolates the transfer part."""
        import jax
        t0 = time.perf_counter()
        try:
            dev, meta = self._out.get(timeout=timeout)
        except queue.Empty:
            self.wait_s += time.perf_counter() - t0
            raise
        t1 = time.perf_counter()
        jax.block_until_ready(dev)
        t2 = time.perf_counter()
        self.wait_s += t2 - t0
        self.xfer_s += t2 - t1
        self.batches += 1
        return dev, meta

    def add_busy(self, seconds: float) -> None:
        self.busy_s += seconds

    def stats(self) -> dict:
        total = self.wait_s + self.busy_s
        return {
            "feed_wait_s": self.wait_s,
            "feed_xfer_s": self.xfer_s,
            "learner_busy_s": self.busy_s,
            "feed_stall_pct": (100.0 * self.wait_s / total) if total else 0.0,
            "feed_xfer_stall_pct": (
                100.0 * self.xfer_s / total) if total else 0.0,
            "batches_fed": self.batches,
        }

    def stop(self) -> None:
        self._stop.set()
