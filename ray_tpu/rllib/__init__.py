"""ray_tpu.rllib: reinforcement learning (RLlib parity, jax-native).

reference: python/ray/rllib — Algorithm/Learner/RLModule/EnvRunner stack
(SURVEY.md §2.3). Learners are JIT'd XLA programs; EnvRunners stay CPU
actors streaming trajectories through the object store (BASELINE.json
north star). Algorithms shipped: PPO, IMPALA, APPO, DQN, SAC, MARWIL,
BC, ES, PG, TD3, DDPG (the reference's 34-algo registry is tracked in SURVEY.md §8.3).
"""

from ray_tpu.rllib.algorithms.algorithm import Algorithm  # noqa: F401
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig  # noqa: F401
from ray_tpu.rllib.algorithms.appo.appo import APPO, APPOConfig  # noqa: F401
from ray_tpu.rllib.algorithms.dqn.dqn import DQN, DQNConfig  # noqa: F401
from ray_tpu.rllib.algorithms.es.es import ES, ESConfig  # noqa: F401
from ray_tpu.rllib.algorithms.pg.pg import PG, PGConfig  # noqa: F401
from ray_tpu.rllib.algorithms.td3.td3 import TD3, TD3Config  # noqa: F401
from ray_tpu.rllib.algorithms.ddpg.ddpg import DDPG, DDPGConfig  # noqa: F401
from ray_tpu.rllib.algorithms.marwil.marwil import (BC, MARWIL,  # noqa: F401
                                                    BCConfig, MARWILConfig)
from ray_tpu.rllib.algorithms.sac.sac import SAC, SACConfig  # noqa: F401
from ray_tpu.rllib.algorithms.impala.impala import (Impala,  # noqa: F401
                                                    ImpalaConfig)
from ray_tpu.rllib.algorithms.ppo.ppo import PPO, PPOConfig  # noqa: F401
from ray_tpu.rllib.algorithms.registry import (  # noqa: F401
    get_algorithm_class, registered_algorithms)
from ray_tpu.rllib.core.catalog import (DiscreteConvModule,  # noqa: F401
                                        DiscreteMLPModule)
from ray_tpu.rllib.core.learner import Learner  # noqa: F401
from ray_tpu.rllib.core.learner_group import LearnerGroup  # noqa: F401
from ray_tpu.rllib.core.rl_module import RLModule  # noqa: F401
from ray_tpu.rllib.env.base import Env, make_env, register_env  # noqa: F401
from ray_tpu.rllib.env import cartpole  # noqa: F401  (registers CartPole-v1)
from ray_tpu.rllib.env import catch_pixels  # noqa: F401  (CatchPixels-v0)
from ray_tpu.rllib.env import minipong  # noqa: F401  (MiniPong-v0)
from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner  # noqa: F401
from ray_tpu.rllib.env.multi_agent import (MultiAgentEnv,  # noqa: F401
                                           make_multi_agent)

__all__ = [
    "Algorithm", "AlgorithmConfig", "PPO", "PPOConfig", "Impala",
    "ImpalaConfig", "APPO", "APPOConfig", "DQN", "DQNConfig",
    "SAC", "SACConfig", "MARWIL", "MARWILConfig", "BC", "BCConfig",
    "ES", "ESConfig", "PG", "PGConfig", "TD3", "TD3Config",
    "DDPG", "DDPGConfig",
    "get_algorithm_class",
    "registered_algorithms", "Learner", "LearnerGroup", "RLModule",
    "DiscreteMLPModule", "DiscreteConvModule", "Env", "register_env",
    "make_env", "SingleAgentEnvRunner", "MultiAgentEnv",
    "make_multi_agent",
]

from ray_tpu._private.usage import record_library_usage as _rlu
_rlu('rllib')
del _rlu
