"""Algorithm base + EnvRunnerSet.

reference parity: rllib/algorithms/algorithm.py:192,555,816 — Algorithm
(a Tune Trainable) whose train() runs one training_step() and folds
env-runner episode metrics into the result; WorkerSet
(evaluation/worker_set.py:82) with sync_weights (:365) and parallel
foreach (:657) becomes EnvRunnerSet here (local runner when
num_env_runners=0, actor runners otherwise).
"""

from __future__ import annotations

import collections
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.catalog import default_module_for
from ray_tpu.rllib.core.learner_group import LearnerGroup
from ray_tpu.rllib.env.base import make_env
from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner


class EnvRunnerSet:
    """Local or actor-based rollout workers (reference WorkerSet)."""

    def __init__(self, config: AlgorithmConfig, module):
        self.config = config
        self._local: Optional[SingleAgentEnvRunner] = None
        self._actors: List[Any] = []
        self._writer = None
        if config.output:
            from ray_tpu.rllib.offline.json_io import JsonWriter
            self._writer = JsonWriter(config.output)
        if config.num_env_runners == 0:
            self._local = SingleAgentEnvRunner(
                config.env, module, config.env_config,
                num_envs=config.num_envs_per_env_runner,
                seed=config.seed, worker_index=0, gamma=config.gamma,
                policy_mapping_fn=config.policy_mapping_fn,
                env_connectors=config.env_connectors,
                action_connectors=config.action_connectors)
        else:
            import ray_tpu
            runner_cls = ray_tpu.remote(SingleAgentEnvRunner)
            self._actors = [
                runner_cls.options(num_cpus=1).remote(
                    config.env, module, config.env_config,
                    num_envs=config.num_envs_per_env_runner,
                    seed=config.seed, worker_index=i + 1,
                    gamma=config.gamma,
                    policy_mapping_fn=config.policy_mapping_fn,
                    env_connectors=config.env_connectors,
                    action_connectors=config.action_connectors)
                for i in range(config.num_env_runners)
            ]

    def __len__(self) -> int:
        return max(1, len(self._actors))

    def sync_weights(self, weights) -> None:
        """reference worker_set.py:365."""
        if self._local is not None:
            self._local.set_weights(weights)
            return
        import ray_tpu
        ray_tpu.get([a.set_weights.remote(weights) for a in self._actors],
                    timeout=300)

    def set_explore_inputs(self, inputs: Dict[str, float]) -> None:
        """Broadcast exploration scalars (epsilon schedules etc.)."""
        if self._local is not None:
            self._local.set_explore_inputs(inputs)
            return
        import ray_tpu
        ray_tpu.get([a.set_explore_inputs.remote(inputs)
                     for a in self._actors], timeout=120)

    def sample_sync(self, num_timesteps_per_runner: int
                    ) -> List[Dict[str, Any]]:
        """reference execution/rollout_ops.py:21
        synchronous_parallel_sample."""
        if self._local is not None:
            frags = [self._local.sample(num_timesteps_per_runner)]
        else:
            import ray_tpu
            frags = ray_tpu.get(
                [a.sample.remote(num_timesteps_per_runner)
                 for a in self._actors], timeout=600)
        if self._writer is not None:
            for f in frags:
                self._writer.write(f)
        return frags

    @property
    def actors(self) -> List[Any]:
        return self._actors

    def stop(self) -> None:  # EnvRunnerSet
        if self._writer is not None:
            self._writer.close()
        if self._local is not None:
            self._local.stop()
        import ray_tpu
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001 - actor already dead
                pass


class Algorithm:
    """Subclasses implement training_step(); train() wraps one step with
    metrics/timing (reference algorithm.py:816 step →
    _run_one_training_iteration :3020)."""

    learner_cls = None  # set by subclass
    ma_learner_cls = None  # multi-agent variant (PPO sets it)
    needs_env_runners = True  # ES overrides: no rollout workers

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        if config.env is None:
            raise ValueError("config.environment(env=...) is required")
        probe = make_env(config.env, config.env_config)
        self.observation_space = probe.observation_space
        self.action_space = probe.action_space
        if config.env_connectors:
            # the module acts on the PIPELINE's output space
            from ray_tpu.rllib.connectors import ConnectorPipeline
            self.observation_space = ConnectorPipeline(
                config.env_connectors).observation_space(
                    self.observation_space)
        probe.close()

        if config.policies:
            # distinct per-agent policies (reference marl_module.py:40)
            from ray_tpu.rllib.core.marl_module import MultiAgentRLModule
            if config.policy_mapping_fn is None:
                raise ValueError(
                    "multi_agent(policies=...) needs a policy_mapping_fn")
            if self.ma_learner_cls is None:
                raise ValueError(
                    f"{type(self).__name__} has no multi-agent learner")
            agents = getattr(probe, "agents", None)
            if agents:
                mapped = {config.policy_mapping_fn(a) for a in agents}
                unused = set(config.policies) - mapped
                if unused:
                    raise ValueError(
                        f"policies {sorted(unused)} are never produced "
                        f"by policy_mapping_fn for agents {agents}")
            self.module = MultiAgentRLModule({
                mid: (mod or self.default_module(
                    self.observation_space, self.action_space))
                for mid, mod in config.policies.items()})
            learner_cls = self.ma_learner_cls
        else:
            self.module = config._custom_module or self.default_module(
                self.observation_space, self.action_space)
            learner_cls = self.learner_cls
        self.learner_group = LearnerGroup(
            lambda: learner_cls(self.module, self.config),
            num_learners=config.num_learners, seed=config.seed)
        if self.needs_env_runners:
            self.env_runners = EnvRunnerSet(config, self.module)
            self.env_runners.sync_weights(
                self.learner_group.get_weights())
        else:  # derivative-free algos (ES) evaluate their own way
            self.env_runners = None

        self._iteration = 0
        self._timesteps_total = 0
        self._episode_returns = collections.deque(
            maxlen=config.metrics_num_episodes_for_smoothing)
        self._episode_lens = collections.deque(
            maxlen=config.metrics_num_episodes_for_smoothing)

    # ---- the per-algorithm core ------------------------------------
    def default_module(self, observation_space, action_space):
        """Module when the user supplies none; algorithms with
        non-actor-critic nets (DQN, SAC) override."""
        return default_module_for(observation_space, action_space,
                                  self.config.model_hiddens)

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    # ---- public loop ------------------------------------------------
    def train(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        self._iteration += 1
        step_results = self.training_step()
        result = {
            "training_iteration": self._iteration,
            "num_env_steps_sampled_lifetime": self._timesteps_total,
            "time_this_iter_s": time.perf_counter() - t0,
            "env_runners": {
                "episode_return_mean": (
                    float(np.mean(self._episode_returns))
                    if self._episode_returns else float("nan")),
                "episode_len_mean": (
                    float(np.mean(self._episode_lens))
                    if self._episode_lens else float("nan")),
                "num_episodes": len(self._episode_returns),
            },
            **step_results,
        }
        # legacy-name aliases (reference keeps both during migration)
        result["episode_reward_mean"] = \
            result["env_runners"]["episode_return_mean"]
        return result

    def _record_episode_metrics(self, batches: List[Dict[str, Any]]
                                ) -> None:
        for b in batches:
            for m in b.get("episode_metrics", []):
                self._episode_returns.append(m["episode_return"])
                self._episode_lens.append(m["episode_len"])

    # ---- checkpointing (Trainable contract: save/restore) -----------
    def _extra_state(self) -> Dict[str, Any]:
        """Algorithm-specific driver state to checkpoint (normalizers,
        target-sync counters ...); subclasses extend."""
        return {}

    def _restore_extra_state(self, extra: Dict[str, Any]) -> None:
        pass

    def save(self, checkpoint_dir: str) -> str:
        import os
        import pickle
        os.makedirs(checkpoint_dir, exist_ok=True)
        state = {
            "learner": self.learner_group.get_state(),
            "iteration": self._iteration,
            "timesteps_total": self._timesteps_total,
            "extra": self._extra_state(),
        }
        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"),
                  "wb") as f:
            pickle.dump(state, f)
        return checkpoint_dir

    def restore(self, checkpoint_dir: str) -> None:
        import os
        import pickle
        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"),
                  "rb") as f:
            state = pickle.load(f)
        self.learner_group.set_state(state["learner"])
        self._iteration = state["iteration"]
        self._timesteps_total = state["timesteps_total"]
        self._restore_extra_state(state.get("extra", {}))
        if self.env_runners is not None:
            self.env_runners.sync_weights(
                self.learner_group.get_weights())

    def stop(self) -> None:
        if self.env_runners is not None:
            self.env_runners.stop()
        self.learner_group.shutdown()
