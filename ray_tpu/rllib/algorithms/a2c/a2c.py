"""A2C: synchronous advantage actor-critic.

reference parity: rllib/algorithms/a2c/a2c.py (A2CConfig over
PPOConfig's on-policy plumbing: microbatch_size accumulating gradients
toward train_batch_size; loss = policy gradient with GAE advantages +
value loss + entropy, a2c_torch_policy.py). Distinctions from PG here: fragment-boundary
bootstrapping through GAE (PG uses whole-episode Monte-Carlo shaped
rollouts; lambda is configurable — lower it below 1.0 for the
bias/variance trade the reference's n-step returns provide) and
microbatched updates — this build maps microbatch_size onto the
learner's minibatch loop (per-microbatch Adam steps rather than the
reference's gradient accumulation; at A2C's single-epoch on-policy
regime the two are equivalent up to Adam's step-size normalization).
"""

from __future__ import annotations

from ray_tpu.rllib.algorithms.pg.pg import PGLearner
from ray_tpu.rllib.algorithms.ppo.ppo import PPO, PPOConfig


class A2CConfig(PPOConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or A2C)
        self.lr = 1e-3
        self.train_batch_size = 1000
        self.microbatch_size = None   # None -> one full-batch pass
        self.minibatch_size = None    # override PPO's 128 default —
        # None means the learner takes ONE full-batch step
        self.num_epochs = 1
        self.lambda_ = 1.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.use_kl_loss = False

    def training(self, *, microbatch_size=None, **kwargs):
        if microbatch_size is not None:
            self.microbatch_size = int(microbatch_size)
        return super().training(**kwargs)


class A2CLearner(PGLearner):
    """Same actor-critic loss as PG (no clip/KL); A2C's identity is the
    sync sample->update loop + bootstrapped advantages."""


class A2C(PPO):
    learner_cls = A2CLearner

    def training_step(self):
        # map microbatch_size onto the minibatch loop for this step
        cfg = self.config
        if cfg.microbatch_size is not None:
            cfg.minibatch_size = cfg.microbatch_size
        return super().training_step()
