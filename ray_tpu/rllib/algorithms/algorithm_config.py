"""AlgorithmConfig: config-as-object builder.

reference parity: rllib/algorithms/algorithm_config.py:118 — chained
.environment()/.env_runners()/.training()/.learners() setters returning
self, .build() producing the Algorithm. Only the knobs this stack
implements are exposed; unknown kwargs raise immediately (the reference
validates centrally too).
"""

from __future__ import annotations

import copy
from typing import Any, Dict, Optional, Type


class AlgorithmConfig:
    def __init__(self, algo_class: Optional[Type] = None):
        self.algo_class = algo_class
        # environment
        self.env: Optional[str] = None
        self.env_config: Dict[str, Any] = {}
        # env runners (reference .env_runners / legacy .rollouts)
        self.num_env_runners: int = 0
        self.num_envs_per_env_runner: int = 1
        self.rollout_fragment_length: int = 200
        # connector pipelines (reference connectors/): vectorized
        # obs/reward transforms + action transforms at the runner
        # boundary; instances are templates — each runner gets its own
        # (pickled) copy of the stateful ones
        self.env_connectors: list = []
        self.action_connectors: list = []
        # training
        self.lr: float = 5e-5
        self.gamma: float = 0.99
        self.lambda_: float = 0.95
        self.train_batch_size: int = 4000
        self.minibatch_size: Optional[int] = 128
        self.num_epochs: int = 30           # reference num_sgd_iter
        self.grad_clip: Optional[float] = None
        self.entropy_coeff: float = 0.0
        self.vf_loss_coeff: float = 1.0
        # learners
        self.num_learners: int = 0
        # module
        self.model_hiddens = (64, 64)
        self._custom_module = None
        # offline data (reference .offline_data(input_=..., output=...))
        self.input_: Optional[str] = None
        self.output: Optional[str] = None
        # multi-agent (reference .multi_agent(policies=...,
        # policy_mapping_fn=...); None => single-policy)
        self.policies = None
        self.policy_mapping_fn = None
        # misc
        self.seed: int = 0
        self.metrics_num_episodes_for_smoothing: int = 100

    # ---- chained setters -------------------------------------------
    def environment(self, env: Optional[str] = None,
                    env_config: Optional[Dict[str, Any]] = None
                    ) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = dict(env_config)
        return self

    def env_runners(self, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None,
                    env_connectors: Optional[list] = None,
                    action_connectors: Optional[list] = None
                    ) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if env_connectors is not None:
            self.env_connectors = list(env_connectors)
        if action_connectors is not None:
            self.action_connectors = list(action_connectors)
        return self

    def training(self, **kwargs: Any) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown training option {k!r}")
            setattr(self, k, v)
        return self

    def learners(self, num_learners: Optional[int] = None
                 ) -> "AlgorithmConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        return self

    def rl_module(self, module=None, model_hiddens=None
                  ) -> "AlgorithmConfig":
        if module is not None:
            self._custom_module = module
        if model_hiddens is not None:
            self.model_hiddens = tuple(model_hiddens)
        return self

    def multi_agent(self, policies=None, policy_mapping_fn=None
                    ) -> "AlgorithmConfig":
        """Distinct per-agent policies (reference
        algorithm_config.py .multi_agent). `policies`: dict
        {module_id: RLModule-or-None} (None => default module built from
        the env's spaces); `policy_mapping_fn(agent_id) -> module_id`
        routes each fixed-roster agent to its module."""
        if policies is not None:
            self.policies = dict(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    def offline_data(self, input_: Optional[str] = None,
                     output: Optional[str] = None) -> "AlgorithmConfig":
        """input_: dir of JsonWriter shards to train from (BC/MARWIL);
        output: dir to write sampled fragments to (any algorithm)."""
        if input_ is not None:
            self.input_ = input_
        if output is not None:
            self.output = output
        return self

    def debugging(self, seed: Optional[int] = None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    # ---- build ------------------------------------------------------
    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def build(self, env: Optional[str] = None):
        if env is not None:
            self.env = env
        if self.algo_class is None:
            raise ValueError("config has no algo_class to build")
        return self.algo_class(self.copy())
