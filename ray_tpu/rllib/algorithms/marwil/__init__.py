from ray_tpu.rllib.algorithms.marwil.marwil import (BC, MARWIL, BCConfig,
                                                    MARWILConfig,
                                                    MARWILLearner)

__all__ = ["MARWIL", "MARWILConfig", "BC", "BCConfig", "MARWILLearner"]
