"""MARWIL (advantage-weighted imitation) + BC (behavior cloning).

reference parity: rllib/algorithms/marwil/marwil.py (MARWILConfig — beta
exponential advantage weighting, vf_coeff, moving-average advantage
normalizer; training_step reads offline JSON input) and
rllib/algorithms/bc/bc.py (BC = MARWIL with beta=0, pure -logp
imitation). Offline fragments are postprocessed with the same GAE used
online, then the weighted-imitation update runs as one jitted program.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.offline.json_io import JsonReader
from ray_tpu.rllib.utils.postprocessing import postprocess_fragment


class MARWILConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or MARWIL)
        self.lr = 1e-4
        self.beta = 1.0                  # 0 => plain behavior cloning
        self.train_batch_size = 2000
        self.minibatch_size = 128
        self.num_epochs = 1
        self.moving_average_sqd_adv_norm_update_rate = 1e-2
        # periodic online evaluation with the learned policy
        self.evaluation_interval: Optional[int] = 10
        self.evaluation_duration = 400   # timesteps per eval round


class MARWILLearner(Learner):
    """exp(beta * normalized advantage)-weighted -logp + value loss
    (reference marwil_torch_policy.py marwil_loss)."""

    def compute_loss(self, params, batch, extra):
        import jax.numpy as jnp

        out = self.module.forward_train(params, batch)
        dist = self.module.action_dist(out["action_dist_inputs"])
        logp = dist.logp(batch["actions"])
        cfg = self.config

        if cfg.beta > 0.0:
            # advantages arrive pre-normalized by the driver's moving
            # average of sqd advantages (reference keeps the same
            # normalizer in the policy)
            weights = jnp.minimum(
                jnp.exp(cfg.beta * batch["advantages"]), 20.0)
            vf = out["vf_preds"]
            vf_loss = jnp.mean((vf - batch["value_targets"]) ** 2)
        else:
            weights = jnp.ones_like(logp)
            vf_loss = jnp.asarray(0.0, jnp.float32)

        entropy = dist.entropy()
        policy_loss = -jnp.mean(weights * logp)
        loss = (policy_loss + cfg.vf_loss_coeff * vf_loss
                - cfg.entropy_coeff * jnp.mean(entropy))
        return loss, {
            "policy_loss": policy_loss, "vf_loss": vf_loss,
            "entropy": jnp.mean(entropy),
            "mean_imitation_weight": jnp.mean(weights),
        }


class MARWIL(Algorithm):
    learner_cls = MARWILLearner

    def __init__(self, config: "MARWILConfig"):
        if not config.input_:
            raise ValueError(
                "MARWIL/BC are offline algorithms: point "
                "config.offline_data(input_=...) at a JsonWriter dir")
        super().__init__(config)
        self._reader = JsonReader(config.input_, seed=config.seed)
        self._sqd_adv_norm = 1.0  # moving average of adv^2

    def _extra_state(self) -> Dict[str, Any]:
        return {"sqd_adv_norm": self._sqd_adv_norm}

    def _restore_extra_state(self, extra: Dict[str, Any]) -> None:
        self._sqd_adv_norm = extra.get("sqd_adv_norm",
                                       self._sqd_adv_norm)

    def _value_fn(self):
        """Jitted V(s) with the CURRENT policy weights (reference MARWIL
        recomputes advantages against the training value function each
        pass, not the recorded behavior values)."""
        if not hasattr(self, "_vf_jit"):
            import jax
            self._vf_jit = jax.jit(
                lambda p, obs: self.module.forward_train(
                    p, {"obs": obs})["vf_preds"])
        return self._vf_jit

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        # --- assemble a train batch from offline fragments -----------
        weights = (self.learner_group.get_weights()
                   if cfg.beta > 0.0 else None)
        parts, rows = [], 0
        while rows < cfg.train_batch_size:
            frag = self._reader.next()
            if weights is not None:
                vf = self._value_fn()
                t, n = frag["rewards"].shape[:2]
                flat_obs = frag["obs"].reshape(
                    (t * n, *frag["obs"].shape[2:]))
                frag = dict(frag)
                frag["vf_preds"] = np.asarray(
                    vf(weights, flat_obs)).reshape(t, n)
                frag["bootstrap_value"] = np.asarray(
                    vf(weights, frag["last_obs"]))
            p = postprocess_fragment(frag, cfg.gamma, cfg.lambda_)
            parts.append(p)
            rows += len(p["obs"])
        batch = {k: np.concatenate([p[k] for p in parts])
                 for k in parts[0]}
        self._timesteps_total += rows

        if cfg.beta > 0.0:
            # normalize by the moving average of squared advantages
            # (reference marwil keeps the same normalizer in-policy,
            # update_averaged_estimate in marwil_torch_policy.py)
            raw_sqd = float(np.mean(batch["advantages"] ** 2))
            batch["advantages"] = (
                batch["advantages"]
                / max(np.sqrt(self._sqd_adv_norm), 1e-4))
            rate = cfg.moving_average_sqd_adv_norm_update_rate
            self._sqd_adv_norm = (1 - rate) * self._sqd_adv_norm \
                + rate * raw_sqd

        stats = self.learner_group.update(
            batch, minibatch_size=cfg.minibatch_size,
            num_iters=cfg.num_epochs, seed=cfg.seed + self._iteration)
        stats["sqd_adv_norm"] = self._sqd_adv_norm

        # --- periodic online evaluation ------------------------------
        if cfg.evaluation_interval and \
                self._iteration % cfg.evaluation_interval == 0:
            self.env_runners.sync_weights(
                self.learner_group.get_weights())
            frags = self.env_runners.sample_sync(
                cfg.evaluation_duration // max(1, len(self.env_runners)))
            self._record_episode_metrics(frags)

        return {"learner": stats, "num_offline_steps_trained": rows}


class BCConfig(MARWILConfig):
    """reference bc.py: BCConfig = MARWILConfig with beta forced to 0."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or BC)
        self.beta = 0.0
        self.vf_loss_coeff = 0.0


class BC(MARWIL):
    pass
