from ray_tpu.rllib.algorithms.ddpg.ddpg import DDPG, DDPGConfig

__all__ = ["DDPG", "DDPGConfig"]
