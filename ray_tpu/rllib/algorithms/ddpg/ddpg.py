"""DDPG: deep deterministic policy gradient.

reference parity: rllib/algorithms/ddpg/ddpg.py — the ancestor TD3
refines (the reference implements TD3 on top of DDPG's policy; this
build inverts the inheritance, same math): every-step policy updates
(policy_delay=1) and NO target-action smoothing noise; twin critics
remain (clipped double-Q hurts nothing and shares the TD3 learner).
"""

from __future__ import annotations

from ray_tpu.rllib.algorithms.td3.td3 import TD3, TD3Config


class DDPGConfig(TD3Config):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or DDPG)
        self.policy_delay = 1      # actor steps every update
        self.target_noise = 0.0    # no smoothing on target actions
        self.target_noise_clip = 0.0


class DDPG(TD3):
    pass
