"""CQL: conservative Q-learning (offline RL on SAC machinery).

reference parity: rllib/algorithms/cql/cql.py (CQLConfig —
min_q_weight, num_actions over SACConfig; offline input required;
the reference's bc_iters actor warm-up is NOT implemented here) and
cql_torch_policy.py (cql_loss: the SAC actor-critic loss plus the
conservative regularizer min_q_weight * (logsumexp_a Q(s,a) - Q(s,
a_data)) estimated over `num_actions` uniform + policy-sampled actions
with importance correction). TPU-first shape: the regularizer joins
SAC's single fused jitted update; offline fragments stream from
JsonReader shards and convert to transition tuples through DQN's exact
n-step/truncation-aware converter.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.dqn.dqn import fragment_to_transitions
from ray_tpu.rllib.algorithms.sac.sac import SAC, SACConfig, SACLearner
from ray_tpu.rllib.offline.json_io import JsonReader


class CQLConfig(SACConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or CQL)
        self.min_q_weight = 5.0       # conservative penalty scale
        self.num_actions = 4          # sampled actions for logsumexp
        self.input_ = None            # offline JSONL dir (required)
        # offline: no env stepping, learn every iteration
        self.num_steps_sampled_before_learning_starts = 0
        self.evaluation_interval = 0
        self.evaluation_duration = 256


class CQLLearner(SACLearner):
    """SAC's fused update + the conservative penalty on both critics."""

    def compute_loss(self, params, batch, extra):
        import jax
        import jax.numpy as jnp
        from jax import lax

        loss, stats = super().compute_loss(params, batch, extra)
        m = self.module
        cfg = self.config
        n = cfg.num_actions
        obs = batch["obs"]
        b = obs.shape[0]
        k_unif, k_pi = jax.random.split(
            jax.random.fold_in(extra["rng"], 991))

        # candidate actions: uniform over the box + current policy
        # samples, with the standard CQL importance corrections
        low = jnp.asarray(m.low)
        high = jnp.asarray(m.high)
        unif = jax.random.uniform(
            k_unif, (n, b, m.act_dim), minval=low, maxval=high)
        rep_obs = jnp.broadcast_to(obs, (n, *obs.shape))
        pi_a, pi_logp = m.sample_action(
            params, rep_obs.reshape(n * b, -1), k_pi)
        # the conservative penalty trains the CRITIC only (reference
        # CQL keeps separate optimizers); with the fused update the
        # reparameterized policy sample must be fenced or the penalty
        # would train the actor to minimize its own Q
        pi_a = lax.stop_gradient(pi_a.reshape(n, b, m.act_dim))
        pi_logp = pi_logp.reshape(n, b)
        # log-uniform density over the box volume
        log_unif = -jnp.sum(jnp.log(high - low))

        def q_of(actions):
            q1, q2 = m.q_values(params, rep_obs.reshape(n * b, -1),
                                actions.reshape(n * b, -1))
            return q1.reshape(n, b), q2.reshape(n, b)

        uq1, uq2 = q_of(unif)
        pq1, pq2 = q_of(pi_a)
        cat1 = jnp.concatenate(
            [uq1 - log_unif, pq1 - lax.stop_gradient(pi_logp)], axis=0)
        cat2 = jnp.concatenate(
            [uq2 - log_unif, pq2 - lax.stop_gradient(pi_logp)], axis=0)
        lse1 = jax.nn.logsumexp(cat1, axis=0) - jnp.log(2 * n)
        lse2 = jax.nn.logsumexp(cat2, axis=0) - jnp.log(2 * n)
        dq1, dq2 = m.q_values(params, obs, batch["actions"])
        cql_term = (jnp.mean(lse1 - dq1) + jnp.mean(lse2 - dq2))
        loss = loss + cfg.min_q_weight * cql_term
        stats = dict(stats)
        stats["cql_loss"] = cql_term
        return loss, stats


class CQL(SAC):
    """Offline training loop: stream recorded fragments -> transition
    tuples -> fused CQL update (no env sampling; reference cql.py
    training_step reads from the offline input)."""

    learner_cls = CQLLearner

    def __init__(self, config: "CQLConfig"):
        if not config.input_:
            raise ValueError(
                "CQL is an offline algorithm: point "
                "config.offline_data(input_=...) at a JsonWriter dir")
        super().__init__(config)
        self._reader = JsonReader(config.input_, seed=config.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        parts, rows = [], 0
        while rows < cfg.train_batch_size:
            frag = self._reader.next()
            tr = fragment_to_transitions(frag, cfg.gamma, cfg.n_step)
            parts.append(tr)
            rows += len(tr["obs"])
        # slice to EXACTLY train_batch_size: variable fragment sizes
        # would otherwise recompile the fused update per new length
        batch = {k: np.concatenate([p[k] for p in parts])
                 [:cfg.train_batch_size] for k in parts[0]}
        rows = cfg.train_batch_size
        self._timesteps_total += rows
        stats = self.learner_group.update(
            batch, seed=cfg.seed + self._iteration)
        # polyak target update: SAC gets this from the replay loop's
        # _after_each_update hook, which this offline loop replaces
        self._after_each_update()

        if cfg.evaluation_interval and \
                self._iteration % cfg.evaluation_interval == 0:
            self.env_runners.sync_weights(
                self.learner_group.get_weights())
            frags = self.env_runners.sample_sync(
                cfg.evaluation_duration // max(1, len(self.env_runners)))
            self._record_episode_metrics(frags)
        return {"learner": stats, "num_offline_steps_trained": rows}
