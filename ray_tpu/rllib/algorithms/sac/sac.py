"""SAC: soft actor-critic for continuous control.

reference parity: rllib/algorithms/sac/sac.py (SACConfig — twin Q,
tau polyak target update, initial_alpha/target_entropy="auto", n_step
replay; training_step shares the DQN replay loop) and
sac_torch_policy.py (actor_critic_loss: squashed-gaussian policy,
min-of-twin-Q targets with entropy bonus, trainable log_alpha against
target entropy). TPU-first shape: actor + critic + alpha losses combine
into ONE jitted update with subtree stop_gradients routing each term's
gradients to its own parameters — one XLA program instead of the
reference's three optimizer round-trips; target nets polyak-update in a
second tiny jitted program.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import numpy as np

from ray_tpu.rllib.algorithms.dqn.dqn import DQN, DQNConfig
from ray_tpu.rllib.core.catalog import _mlp_apply, _mlp_init
from ray_tpu.rllib.core.rl_module import RLModule
from ray_tpu.rllib.core.target_learner import (ContinuousReplayAlgoMixin,
                                               PolyakTargetLearner)

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


class SACConfig(DQNConfig):
    """Shares DQN's replay-loop knobs (buffer_size, n_step,
    prioritized_replay*, training_intensity, learning-start threshold).
    DQN-only knobs (dueling, double_q, epsilon_*,
    target_network_update_freq) are inert: SAC's stochastic policy
    explores and its targets polyak-update every gradient step (tau)."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or SAC)
        self.lr = 3e-4
        self.train_batch_size = 256
        self.rollout_fragment_length = 1
        self.tau = 0.005
        self.initial_alpha = 1.0
        self.target_entropy = "auto"     # -> -action_dim
        self.num_steps_sampled_before_learning_starts = 1500
        # epsilon schedule is inert for SAC (stochastic policy explores)
        self.initial_epsilon = self.final_epsilon = 0.0


class SquashedGaussianModule(RLModule):
    """tanh-squashed gaussian policy + twin Q(s, a) critics
    (reference sac_torch_model.py). Actions rescale to [low, high]."""

    def __init__(self, obs_dim: int, act_dim: int, low, high,
                 hiddens: Sequence[int] = (256, 256)):
        self.obs_dim = obs_dim
        self.act_dim = act_dim
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)
        self.hiddens = tuple(hiddens)

    # ---- params -----------------------------------------------------
    def init_params(self, key) -> Dict[str, Any]:
        import jax
        kp, k1, k2 = jax.random.split(key, 3)
        pi_sizes = [self.obs_dim, *self.hiddens, 2 * self.act_dim]
        q_sizes = [self.obs_dim + self.act_dim, *self.hiddens, 1]
        return {"pi": _mlp_init(kp, pi_sizes),
                "q1": _mlp_init(k1, q_sizes, scale_last=1.0),
                "q2": _mlp_init(k2, q_sizes, scale_last=1.0)}

    # ---- pure heads -------------------------------------------------
    def pi_dist_inputs(self, params, obs):
        import jax.numpy as jnp
        out = _mlp_apply(params["pi"], obs)
        mean, log_std = jnp.split(out, 2, axis=-1)
        return mean, jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)

    def sample_action(self, params, obs, key):
        """Reparameterized squashed sample -> (action, logp)."""
        import jax
        import jax.numpy as jnp
        mean, log_std = self.pi_dist_inputs(params, obs)
        std = jnp.exp(log_std)
        u = mean + std * jax.random.normal(key, mean.shape)
        logp_u = jnp.sum(
            -0.5 * (((u - mean) / std) ** 2 + 2 * log_std
                    + jnp.log(2 * jnp.pi)), axis=-1)
        t = jnp.tanh(u)
        scale = (self.high - self.low) / 2.0
        action = t * scale + (self.high + self.low) / 2.0
        logp = logp_u - jnp.sum(
            jnp.log(scale * (1 - t ** 2) + 1e-6), axis=-1)
        return action, logp

    def q_values(self, params, obs, actions):
        import jax.numpy as jnp
        x = jnp.concatenate(
            [obs, actions.astype(jnp.float32)], axis=-1)
        q1 = _mlp_apply(params["q1"], x)[..., 0]
        q2 = _mlp_apply(params["q2"], x)[..., 0]
        return q1, q2

    # ---- RLModule contract ------------------------------------------
    def forward_train(self, params, batch):
        import jax.numpy as jnp
        mean, log_std = self.pi_dist_inputs(params, batch["obs"])
        return {"action_dist_inputs": jnp.concatenate(
                    [mean, log_std], axis=-1),
                # replay path bootstraps at update time; no V head
                "vf_preds": jnp.zeros(mean.shape[:-1], jnp.float32)}

    def forward_exploration(self, params, batch, key):
        out = self.forward_train(params, batch)
        actions, logp = self.sample_action(params, batch["obs"], key)
        out["actions"] = actions
        out["action_logp"] = logp
        return out

    def forward_inference(self, params, batch):
        import jax.numpy as jnp
        out = self.forward_train(params, batch)
        mean, _ = self.pi_dist_inputs(params, batch["obs"])
        scale = (self.high - self.low) / 2.0
        out["actions"] = jnp.tanh(mean) * scale + \
            (self.high + self.low) / 2.0
        return out


class SACLearner(PolyakTargetLearner):
    """One jitted update for critic + actor + alpha (reference
    sac_torch_policy.py actor_critic_loss + optimizer_fn's three Adams).
    Target scaffolding (polyak, rng, checkpointing) comes from
    PolyakTargetLearner."""

    target_keys = ["q1", "q2"]
    rng_salt = 777

    def _post_build(self, seed: int) -> None:
        import jax
        import jax.numpy as jnp
        with self._state_lock:
            # log_alpha joins the trainable pytree; Adam state was built
            # in super().build BEFORE this insert, so rebuild it
            self._params["log_alpha"] = self._maybe_replicate(
                jnp.asarray(np.log(self.config.initial_alpha),
                            jnp.float32))
            if getattr(self, "_distributed", False):
                # rebuild Adam state on host then re-replicate every
                # leaf (matches build_distributed's layout exactly)
                host_params = jax.device_get(self._params)
                self._opt_state = jax.tree.map(
                    self._replicate_host,
                    self._optimizer.init(host_params))
            else:
                self._opt_state = self._optimizer.init(self._params)
        super()._post_build(seed)
        act_dim = self.module.act_dim
        self.target_entropy = (-float(act_dim)
                               if self.config.target_entropy == "auto"
                               else float(self.config.target_entropy))

    def _maybe_replicate(self, x):
        if getattr(self, "_distributed", False):
            return self._replicate_host(np.asarray(x))
        return x

    def compute_loss(self, params, batch, extra):
        import jax
        import jax.numpy as jnp
        from jax import lax

        m: SquashedGaussianModule = self.module
        cfg = self.config
        k_next, k_pi = jax.random.split(extra["rng"])
        alpha = jnp.exp(params["log_alpha"])

        # ---- critic target: r + gamma^n (1-d) (minQ' - a*logp') -----
        next_a, next_logp = m.sample_action(params, batch["next_obs"],
                                            k_next)
        tq1, tq2 = m.q_values(extra["target"], batch["next_obs"], next_a)
        q_next = jnp.minimum(tq1, tq2) - \
            lax.stop_gradient(alpha) * next_logp
        target = batch["rewards"] + batch["discounts"] * \
            (1.0 - batch["dones"]) * q_next
        target = lax.stop_gradient(target)
        q1, q2 = m.q_values(params, batch["obs"], batch["actions"])
        # per-sample importance weights when prioritized replay is on
        w = batch.get("weights")
        td_sq = 0.5 * ((q1 - target) ** 2 + (q2 - target) ** 2)
        critic_loss = jnp.mean(td_sq * w) if w is not None \
            else jnp.mean(td_sq)

        # ---- actor: alpha*logp - minQ(s, a~pi), Q params frozen -----
        pi_a, pi_logp = m.sample_action(params, batch["obs"], k_pi)
        q_sg = {"q1": jax.tree.map(lax.stop_gradient, params["q1"]),
                "q2": jax.tree.map(lax.stop_gradient, params["q2"])}
        pq1, pq2 = m.q_values(q_sg, batch["obs"], pi_a)
        actor_loss = jnp.mean(
            lax.stop_gradient(alpha) * pi_logp - jnp.minimum(pq1, pq2))

        # ---- alpha: match target entropy ----------------------------
        alpha_loss = -jnp.mean(
            params["log_alpha"]
            * lax.stop_gradient(pi_logp + self.target_entropy))

        loss = critic_loss + actor_loss + alpha_loss
        stats = {
            "critic_loss": critic_loss, "actor_loss": actor_loss,
            "alpha_loss": alpha_loss, "alpha": alpha,
            "mean_q": jnp.mean(jnp.minimum(q1, q2)),
            "entropy": -jnp.mean(pi_logp),
            # new priorities: mean abs TD over the twin critics
            # (reference sac_torch_policy td_error output)
            "td_error": 0.5 * (jnp.abs(q1 - target)
                               + jnp.abs(q2 - target)),
        }
        if "batch_indexes" in batch:
            stats["td_indexes"] = batch["batch_indexes"]
        return loss, stats

class SAC(ContinuousReplayAlgoMixin, DQN):
    """Runs DQN's shared replay loop with the continuous-control hooks
    (one gradient step per env step, polyak targets every update;
    reference SAC extends DQN the same way, sac.py)."""

    learner_cls = SACLearner

    def default_module(self, observation_space, action_space):
        if len(observation_space.shape) != 1 or \
                not hasattr(action_space, "low"):
            raise NotImplementedError(
                f"SAC ships a squashed-gaussian MLP for 1-D obs and Box "
                f"actions; got obs={observation_space} "
                f"act={action_space}. Pass a custom module via "
                f"config.rl_module(module=...).")
        hiddens = self.config.model_hiddens
        return SquashedGaussianModule(
            observation_space.shape[0], action_space.shape[0],
            action_space.low, action_space.high, hiddens)
