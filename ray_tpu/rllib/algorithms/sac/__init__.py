from ray_tpu.rllib.algorithms.sac.sac import (SAC, SACConfig, SACLearner,
                                              SquashedGaussianModule)

__all__ = ["SAC", "SACConfig", "SACLearner", "SquashedGaussianModule"]
