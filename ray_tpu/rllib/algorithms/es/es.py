"""ES: OpenAI-style evolution strategies (derivative-free).

reference parity: rllib/algorithms/es/es.py (ES Algorithm: driver holds
flat params; Worker actors evaluate mirrored gaussian perturbations and
return episode rewards; the update is the rank-weighted sum of noise,
es.py _train + optimizers.py Adam; utils.py compute_centered_ranks).
TPU-frame: perturbation noise regenerates from integer seeds on both
sides (only seeds + returns cross the object store, reference
SharedNoiseTable serves the same purpose), episode policy forwards run
jitted on the worker CPU.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner


class ESConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or ES)
        self.lr = 0.02
        self.sigma = 0.05               # perturbation stddev
        self.num_perturbations = 32     # mirrored pairs per iteration
        self.num_workers = 0            # 0 -> evaluate in-process
        self.episode_horizon = 1000
        self.l2_coeff = 0.005
        self.report_length = 10


def compute_centered_ranks(x: np.ndarray) -> np.ndarray:
    """reference es/utils.py: ranks scaled to [-0.5, 0.5]."""
    ranks = np.empty(x.size, dtype=np.float64)
    ranks[x.ravel().argsort()] = np.arange(x.size)
    ranks = ranks.reshape(x.shape) / (x.size - 1) - 0.5
    return ranks


class _ESLearner(Learner):
    """Parameter container only — ES has no gradient loss; the driver
    applies rank-weighted noise updates directly to the weights."""

    def compute_loss(self, params, batch, extra):  # pragma: no cover
        raise NotImplementedError("ES does not use gradient updates")


class ESEvalWorker:
    """Evaluates mirrored perturbations: noise regenerates from seeds."""

    def __init__(self, env_name: str, env_config: Optional[dict],
                 module: Any, sigma: float, horizon: int):
        import jax

        from ray_tpu._private import worker as worker_mod
        w = worker_mod.global_worker_or_none()
        if w is not None and w.mode == "worker":
            # remote-actor path: fresh process, pin rollouts to CPU.
            # NEVER in-process — that would flip the driver's global
            # platform after its learner initialized on TPU.
            jax.config.update("jax_platforms", "cpu")
        from jax.flatten_util import ravel_pytree

        from ray_tpu.rllib.env.base import make_env
        self.env = make_env(env_name, env_config)
        self.module = module
        self.sigma = sigma
        self.horizon = horizon
        template = module.init_params(jax.random.PRNGKey(0))
        flat, self._unravel = ravel_pytree(template)
        self.dim = flat.shape[0]
        self._infer = jax.jit(
            lambda p, obs: module.forward_inference(
                p, {"obs": obs[None]})["actions"][0])

    def _episode_return(self, flat_params: np.ndarray,
                        ep_seed: int) -> Tuple[float, int]:
        import jax

        params = self._unravel(flat_params)
        obs, _ = self.env.reset(ep_seed)
        total, steps = 0.0, 0
        for _ in range(self.horizon):
            # device_get, not np.asarray: the one sanctioned sync in
            # the per-step rollout loop
            action = jax.device_get(self._infer(params, np.asarray(obs)))
            obs, r, term, trunc, _ = self.env.step(action)
            total += float(r)
            steps += 1
            if term or trunc:
                break
        return total, steps

    def evaluate(self, flat_params: np.ndarray, noise_seeds: List[int],
                 ep_seed: int) -> List[Dict[str, Any]]:
        out = []
        for seed in noise_seeds:
            noise = np.random.default_rng(seed).standard_normal(
                self.dim).astype(np.float32)
            r_pos, s1 = self._episode_return(
                flat_params + self.sigma * noise, ep_seed)
            r_neg, s2 = self._episode_return(
                flat_params - self.sigma * noise, ep_seed)
            out.append({"seed": seed, "r_pos": r_pos, "r_neg": r_neg,
                        "steps": s1 + s2})
        return out


class ES(Algorithm):
    learner_cls = _ESLearner
    needs_env_runners = False  # ES evaluates perturbations itself

    def __init__(self, config: "ESConfig"):
        super().__init__(config)
        import jax
        from jax.flatten_util import ravel_pytree
        import optax

        weights = self.learner_group.get_weights()
        flat, self._unravel = ravel_pytree(weights)
        # float32 throughout: jax canonicalizes f64 away (x64 off), so
        # a wider accumulator here would be silently downcast anyway;
        # theta lives on the host (numpy optimizer loop), so force the
        # flattened weights across explicitly once
        self._theta = np.asarray(jax.device_get(flat), np.float32)
        self.dim = self._theta.shape[0]
        self._opt = optax.adam(config.lr)
        self._opt_state = self._opt.init(self._theta)
        self._rng = np.random.default_rng(config.seed)
        self._eval_workers: List[Any] = []
        if config.num_workers > 0:
            import ray_tpu
            cls = ray_tpu.remote(ESEvalWorker)
            self._eval_workers = [
                cls.options(num_cpus=1).remote(
                    config.env, config.env_config, self.module,
                    config.sigma, config.episode_horizon)
                for _ in range(config.num_workers)]
        else:
            self._local_eval = ESEvalWorker(
                config.env, config.env_config, self.module,
                config.sigma, config.episode_horizon)

    def _evaluate_all(self, seeds: List[int], ep_seed: int
                      ) -> List[Dict[str, Any]]:
        flat32 = self._theta.astype(np.float32)
        if not self._eval_workers:
            return self._local_eval.evaluate(flat32, seeds, ep_seed)
        import ray_tpu
        n = len(self._eval_workers)
        chunks = [seeds[i::n] for i in range(n)]
        refs = [w.evaluate.remote(flat32, chunk, ep_seed)
                for w, chunk in zip(self._eval_workers, chunks) if chunk]
        return [r for part in ray_tpu.get(refs, timeout=600)
                for r in part]

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        seeds = [int(s) for s in
                 self._rng.integers(0, 2 ** 31 - 1,
                                    cfg.num_perturbations)]
        ep_seed = int(self._rng.integers(0, 2 ** 31 - 1))
        results = self._evaluate_all(seeds, ep_seed)

        returns = np.array([[r["r_pos"], r["r_neg"]] for r in results])
        ranks = compute_centered_ranks(returns)
        # rank-weighted noise combination (reference es.py _train):
        # g = 1/(n*sigma) * sum_i (rank+_i - rank-_i) * eps_i
        grad = np.zeros(self.dim)
        for r, (w_pos, w_neg) in zip(results, ranks):
            noise = np.random.default_rng(r["seed"]).standard_normal(
                self.dim)
            grad += (w_pos - w_neg) * noise
        grad /= len(results) * cfg.sigma
        # ascent on reward, with L2 pull toward 0 (reference l2_coeff)
        step = (-(grad - cfg.l2_coeff * self._theta)).astype(np.float32)
        updates, self._opt_state = self._opt.update(step, self._opt_state)
        self._theta = np.asarray(self._theta + updates, np.float32)

        self.learner_group.set_weights(self._unravel(self._theta))
        self._timesteps_total += int(sum(r["steps"] for r in results))
        for r in results:
            for ret in (r["r_pos"], r["r_neg"]):
                self._episode_returns.append(ret)
                self._episode_lens.append(r["steps"] // 2)
        mean_ret = float(returns.mean())
        return {"learner": {"mean_perturbation_return": mean_ret,
                            "theta_norm": float(
                                np.linalg.norm(self._theta))},
                "num_env_steps_sampled":
                    int(sum(r["steps"] for r in results))}

    def _extra_state(self) -> Dict[str, Any]:
        return {"theta": self._theta, "opt_state": self._opt_state}

    def _restore_extra_state(self, extra: Dict[str, Any]) -> None:
        if "theta" in extra:
            self._theta = extra["theta"]
            self._opt_state = extra["opt_state"]

    def stop(self) -> None:
        import ray_tpu
        for w in self._eval_workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001 - worker already dead
                pass
        local = getattr(self, "_local_eval", None)
        if local is not None:
            local.env.close()
        super().stop()
