from ray_tpu.rllib.algorithms.appo.appo import APPO, APPOConfig  # noqa: F401

__all__ = ["APPO", "APPOConfig"]
