"""APPO: asynchronous PPO — IMPALA's pipeline with PPO's clipped loss.

reference parity: rllib/algorithms/appo/appo.py — APPO subclasses Impala
(the async sampling architecture, learner thread, broadcast machinery
are shared) and swaps the learner for a clipped-surrogate objective
whose advantages come from V-trace (appo_torch_policy / APPOLearner).
"""

from __future__ import annotations

from ray_tpu.rllib.algorithms.impala.impala import (Impala, ImpalaConfig,
                                                    ImpalaLearner)


class APPOConfig(ImpalaConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or APPO)
        self.clip_param = 0.3
        # APPO defaults differ from IMPALA's (reference appo.py):
        self.lr = 3e-4
        self.entropy_coeff = 0.005


class APPOLearner(ImpalaLearner):
    """PPO clipped surrogate over V-trace advantages (reference
    appo_torch_policy.py loss: ratio clamped to [1-eps, 1+eps] against
    vtrace pg_advantages, value targets = vtrace vs)."""

    def compute_loss(self, params, batch, extra):
        import jax.numpy as jnp

        dist, _target_logp, log_rhos, values, vtrace = \
            self._vtrace_prelude(params, batch)
        ratio = jnp.exp(log_rhos)
        eps = self.config.clip_param
        adv = vtrace.pg_advantages
        surrogate = jnp.minimum(
            ratio * adv, jnp.clip(ratio, 1.0 - eps, 1.0 + eps) * adv)
        pg_loss = -jnp.mean(surrogate)
        vf_loss = 0.5 * jnp.mean((vtrace.vs - values) ** 2)
        entropy = jnp.mean(dist.entropy())
        loss = (pg_loss + self.config.vf_loss_coeff * vf_loss
                - self.config.entropy_coeff * entropy)
        return loss, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                      "entropy": entropy,
                      "mean_ratio": jnp.mean(ratio)}


class APPO(Impala):
    learner_cls = APPOLearner
