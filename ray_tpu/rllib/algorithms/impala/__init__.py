from ray_tpu.rllib.algorithms.impala.impala import (Impala, ImpalaConfig,  # noqa: F401
                                                    ImpalaLearner)
from ray_tpu.rllib.algorithms.impala.vtrace import from_importance_weights  # noqa: F401
