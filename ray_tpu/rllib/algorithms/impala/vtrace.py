"""V-trace off-policy correction (Espeholt et al. 2018), in jax.

reference parity: rllib/algorithms/impala/vtrace_torch.py:251
(from_importance_weights) / :87 (from_logits). Time-major [T, B] arrays;
the backward recursion is a `lax.scan` in reverse — one XLA program, no
Python loop.
"""

from __future__ import annotations

from typing import NamedTuple


class VTraceReturns(NamedTuple):
    vs: object             # [T, B] value targets
    pg_advantages: object  # [T, B] policy-gradient advantages


def from_importance_weights(log_rhos, discounts, rewards, values,
                            bootstrap_value,
                            clip_rho_threshold: float = 1.0,
                            clip_pg_rho_threshold: float = 1.0
                            ) -> VTraceReturns:
    """All inputs time-major [T, B]; bootstrap_value [B].

    discounts must already include termination masking
    (gamma * (1 - done)).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    rhos = jnp.exp(log_rhos)
    clipped_rhos = jnp.minimum(rhos, clip_rho_threshold)
    cs = jnp.minimum(rhos, 1.0)

    values_t_plus_1 = jnp.concatenate(
        [values[1:], bootstrap_value[None]], axis=0)
    deltas = clipped_rhos * (
        rewards + discounts * values_t_plus_1 - values)

    def backward(acc, xs):
        delta_t, discount_t, c_t = xs
        acc = delta_t + discount_t * c_t * acc
        return acc, acc

    _, vs_minus_v = lax.scan(
        backward, jnp.zeros_like(bootstrap_value),
        (deltas, discounts, cs), reverse=True)
    vs = vs_minus_v + values

    vs_t_plus_1 = jnp.concatenate(
        [vs[1:], bootstrap_value[None]], axis=0)
    clipped_pg_rhos = jnp.minimum(rhos, clip_pg_rho_threshold)
    pg_advantages = clipped_pg_rhos * (
        rewards + discounts * vs_t_plus_1 - values)

    return VTraceReturns(vs=jax.lax.stop_gradient(vs),
                         pg_advantages=jax.lax.stop_gradient(
                             pg_advantages))
