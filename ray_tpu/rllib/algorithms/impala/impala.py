"""IMPALA: async actor-critic with V-trace off-policy correction.

reference parity: rllib/algorithms/impala/impala.py:68 (ImpalaConfig),
:559 (Impala), training_step :692-780 — async sample gathering from
runners with in-flight requests (FaultTolerantActorManager), V-trace
learner updates, targeted weight sync only to the runners whose batches
were consumed (:775); ImpalaLearner (impala_learner.py:52).
Tree-aggregation actors (:1247) are not needed at this scale and the
mixin replay is left to config.replay_proportion=0 semantics.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.impala.vtrace import from_importance_weights
from ray_tpu.rllib.core.learner import Learner


class ImpalaConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or Impala)
        self.lr = 5e-4
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.clip_rho_threshold = 1.0
        self.clip_pg_rho_threshold = 1.0
        self.rollout_fragment_length = 50
        self.train_batch_size = 500
        self.grad_clip = 40.0
        self.max_requests_in_flight_per_env_runner = 2
        self.broadcast_interval = 1


class ImpalaLearner(Learner):
    """V-trace actor-critic loss on time-major sequence batches."""

    def compute_loss(self, params, batch, extra):
        import jax.numpy as jnp

        t, b = batch["actions"].shape
        obs_flat = batch["obs"].reshape((t * b,) + batch["obs"].shape[2:])
        out = self.module.forward_train(params, {"obs": obs_flat})
        logits = out["action_dist_inputs"].reshape(
            (t, b) + out["action_dist_inputs"].shape[1:])
        values = out["vf_preds"].reshape((t, b))
        dist = self.module.action_dist(logits)
        target_logp = dist.logp(batch["actions"])

        log_rhos = target_logp - batch["behaviour_logp"]
        discounts = self.config.gamma * (
            1.0 - batch["dones"].astype(jnp.float32))
        vtrace = from_importance_weights(
            log_rhos, discounts, batch["rewards"], values,
            batch["bootstrap_value"],
            self.config.clip_rho_threshold,
            self.config.clip_pg_rho_threshold)

        pg_loss = -jnp.mean(target_logp * vtrace.pg_advantages)
        vf_loss = 0.5 * jnp.mean((vtrace.vs - values) ** 2)
        entropy = jnp.mean(dist.entropy())
        loss = (pg_loss + self.config.vf_loss_coeff * vf_loss
                - self.config.entropy_coeff * entropy)
        return loss, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                      "entropy": entropy}

    def update(self, batch, minibatch_size=None, num_iters=1, seed=0):
        """Sequence batches update in one full-batch step (the reference
        ImpalaLearner also consumes whole trajectories per update)."""
        assert self._update_fn is not None, "call build() first"
        self._params, self._opt_state, stats = self._update_fn(
            self._params, self._opt_state, batch, self.extra_inputs())
        return {k: float(v) for k, v in stats.items()}


def _to_timemajor(fragment: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Runner fragments are already [T, N, ...] time-major; rename
    columns to the learner's contract."""
    return {
        "obs": fragment["obs"],
        "actions": fragment["actions"],
        "rewards": fragment["rewards"],
        "dones": (fragment["terminateds"] | fragment["truncateds"]),
        "behaviour_logp": fragment["action_logp"],
        "bootstrap_value": fragment["bootstrap_value"],
    }


class Impala(Algorithm):
    learner_cls = ImpalaLearner

    def __init__(self, config):
        super().__init__(config)
        self._inflight: Dict[Any, Any] = {}   # ref -> runner actor

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        if not self.env_runners.actors:
            # synchronous degenerate mode (num_env_runners=0)
            fragments = self.env_runners.sample_sync(
                cfg.rollout_fragment_length
                * cfg.num_envs_per_env_runner)
            self._record_episode_metrics(fragments)
            stats = {}
            for f in fragments:
                self._timesteps_total += f["actions"].size
                stats = self.learner_group.update(_to_timemajor(f))
            self.env_runners.sync_weights(
                self.learner_group.get_weights())
            return {"learner": stats,
                    "num_env_steps_trained": sum(
                        f["actions"].size for f in fragments)}

        import ray_tpu
        per_request = cfg.rollout_fragment_length \
            * cfg.num_envs_per_env_runner

        # keep every runner saturated with in-flight sample requests
        # (reference impala.py:692-706 async request management)
        counts: Dict[int, int] = {}
        for ref, actor in self._inflight.items():
            counts[id(actor)] = counts.get(id(actor), 0) + 1
        for actor in self.env_runners.actors:
            while counts.get(id(actor), 0) < \
                    cfg.max_requests_in_flight_per_env_runner:
                self._inflight[actor.sample.remote(per_request)] = actor
                counts[id(actor)] = counts.get(id(actor), 0) + 1

        ready, _ = ray_tpu.wait(
            list(self._inflight), num_returns=1, timeout=60.0)
        stats: Dict[str, float] = {}
        trained = 0
        touched: List[Any] = []
        for ref in ready:
            actor = self._inflight.pop(ref)
            fragment = ray_tpu.get(ref)
            self._record_episode_metrics([fragment])
            self._timesteps_total += fragment["actions"].size
            trained += fragment["actions"].size
            stats = self.learner_group.update(_to_timemajor(fragment))
            touched.append(actor)
            # immediately re-request from this runner
            self._inflight[actor.sample.remote(per_request)] = actor

        # targeted weight sync to the runners whose batches were trained
        # on (reference impala.py:775-780)
        if touched and self._iteration % cfg.broadcast_interval == 0:
            weights = self.learner_group.get_weights()
            ray_tpu.get([a.set_weights.remote(weights) for a in touched],
                        timeout=300)
        return {"learner": stats, "num_env_steps_trained": trained}

    def stop(self) -> None:
        self._inflight.clear()
        super().stop()
