"""IMPALA: async actor-critic with V-trace off-policy correction.

reference parity: rllib/algorithms/impala/impala.py:68 (ImpalaConfig),
:559 (Impala), training_step :692-780 — async sample gathering with
bounded in-flight requests per runner (FaultTolerantActorManager),
fragments buffered up to `train_batch_size`, a background learner thread
decoupling updates from the sample loop (the reference's learner thread,
impala.py legacy _LearnerThread / async LearnerGroup updates), mixin
replay (`replay_proportion` over a bounded slot buffer, reference
MixInMultiAgentReplayBuffer), and targeted weight sync only to runners
whose batches were consumed (:775); ImpalaLearner (impala_learner.py:52).
Tree-aggregation actors (:1247) are not needed at this scale.
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu._private import spans as _spans
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.impala.vtrace import from_importance_weights
from ray_tpu.rllib.core.learner import Learner


class ImpalaConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or Impala)
        self.lr = 5e-4
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.clip_rho_threshold = 1.0
        self.clip_pg_rho_threshold = 1.0
        self.rollout_fragment_length = 50
        self.train_batch_size = 500
        self.grad_clip = 40.0
        self.max_requests_in_flight_per_env_runner = 2
        self.broadcast_interval = 1
        # mixin replay (reference impala.py replay_proportion /
        # replay_buffer_num_slots): ratio of replayed to fresh fragments
        # mixed into each train batch.
        self.replay_proportion = 0.0
        self.replay_buffer_num_slots = 16
        # bounded learner queue: sampling backpressures on a slow learner
        self.learner_queue_size = 4


class ImpalaLearner(Learner):
    """V-trace actor-critic loss on time-major sequence batches."""

    def _vtrace_prelude(self, params, batch):
        """Shared forward + V-trace computation (used by IMPALA's
        policy-gradient loss and APPO's clipped surrogate)."""
        import jax.numpy as jnp

        t, b = batch["actions"].shape
        obs_flat = batch["obs"].reshape((t * b,) + batch["obs"].shape[2:])
        out = self.module.forward_train(params, {"obs": obs_flat})
        logits = out["action_dist_inputs"].reshape(
            (t, b) + out["action_dist_inputs"].shape[1:])
        values = out["vf_preds"].reshape((t, b))
        dist = self.module.action_dist(logits)
        target_logp = dist.logp(batch["actions"])

        log_rhos = target_logp - batch["behaviour_logp"]
        discounts = self.config.gamma * (
            1.0 - batch["dones"].astype(jnp.float32))
        vtrace = from_importance_weights(
            log_rhos, discounts, batch["rewards"], values,
            batch["bootstrap_value"],
            self.config.clip_rho_threshold,
            self.config.clip_pg_rho_threshold)
        return dist, target_logp, log_rhos, values, vtrace

    def compute_loss(self, params, batch, extra):
        import jax.numpy as jnp

        dist, target_logp, _log_rhos, values, vtrace = \
            self._vtrace_prelude(params, batch)
        pg_loss = -jnp.mean(target_logp * vtrace.pg_advantages)
        vf_loss = 0.5 * jnp.mean((vtrace.vs - values) ** 2)
        entropy = jnp.mean(dist.entropy())
        loss = (pg_loss + self.config.vf_loss_coeff * vf_loss
                - self.config.entropy_coeff * entropy)
        return loss, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                      "entropy": entropy}

    def update(self, batch, minibatch_size=None, num_iters=1, seed=0):
        """Sequence batches update in one full-batch step (the reference
        ImpalaLearner also consumes whole trajectories per update).

        Stats lag one update: forcing the fresh stats would block the
        host on the device once per scalar (expensive when dispatch goes
        over a tunnel), so the host copy is started asynchronously and
        the PREVIOUS update's (already-landed) stats are returned."""
        import jax

        assert self._update_fn is not None, "call build() first"
        with self._state_lock:
            self._params, self._opt_state, stats = self._update_fn(
                self._params, self._opt_state, batch, self.extra_inputs())
        for v in stats.values():
            if hasattr(v, "copy_to_host_async"):
                v.copy_to_host_async()
        self._stage_weights_async()
        prev = getattr(self, "_pending_stats", None)
        self._pending_stats = stats
        if prev is None:
            prev = stats
        return {k: float(v) for k, v in jax.device_get(prev).items()}

    def data_axis_for(self, key: str) -> int:
        # time-major [T, B] sequences: the env/batch axis is 1; the
        # per-sequence bootstrap values are [B].
        return 0 if key == "bootstrap_value" else 1


def _to_timemajor(fragment: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Runner fragments are already [T, N, ...] time-major; rename
    columns to the learner's contract."""
    return {
        "obs": fragment["obs"],
        "actions": fragment["actions"],
        "rewards": fragment["rewards"],
        "dones": (fragment["terminateds"] | fragment["truncateds"]),
        "behaviour_logp": fragment["action_logp"],
        "bootstrap_value": fragment["bootstrap_value"],
    }


def _batch_axis(key: str) -> int:
    """Concat axis for time-major [T, B] columns ([B] bootstrap)."""
    return 0 if key == "bootstrap_value" else 1


def _concat_fragments(frags: List[Dict[str, np.ndarray]]
                      ) -> Dict[str, np.ndarray]:
    """Stack same-T fragments along the batch (env) axis."""
    out: Dict[str, np.ndarray] = {}
    for k in frags[0]:
        axis = _batch_axis(k)
        out[k] = frags[0][k] if len(frags) == 1 else np.concatenate(
            [f[k] for f in frags], axis=axis)
    return out


class Impala(Algorithm):
    learner_cls = ImpalaLearner

    def __init__(self, config):
        super().__init__(config)
        self._mgr = None                      # built on first async step
        self._fresh: List[Dict[str, np.ndarray]] = []
        self._fresh_steps = 0
        self._replay: collections.deque = collections.deque(
            maxlen=config.replay_buffer_num_slots)
        self._replay_rng = np.random.default_rng(config.seed or 0)
        self._train_queue: "queue.Queue" = queue.Queue(
            maxsize=config.learner_queue_size)
        self._learner_thread: Optional[threading.Thread] = None
        self._stop_event = threading.Event()
        self._stats_lock = threading.Lock()
        self._learner_stats: Dict[str, float] = {}
        self._learner_error: Optional[BaseException] = None
        self._steps_trained = 0
        self._updates_done = 0
        self._feed = None
        self._stage = None                    # HostStage (local learner)
        self._last_reported_trained = 0
        self._weights_version = 0
        self._synced_version = 0
        self._touched_ids: set = set()

    # ---- background learner (reference legacy _LearnerThread) --------

    def _ensure_learner_thread(self) -> None:
        if self._learner_thread is not None:
            return
        self._learner_thread = threading.Thread(
            target=self._learner_loop, daemon=True, name="impala-learner")
        self._learner_thread.start()

    def _learner_loop(self) -> None:
        import time as _time

        # goodput ledger for the learner thread: sampling starvation
        # is feed_stall, LearnerGroup.update opens productive_step,
        # unwrapped remainder is honest idle
        from ray_tpu._private import goodput
        goodput.ledger("impala").bind()
        # Local learner: double-buffered host→HBM prefetch so transfer k+1
        # overlaps update k (SURVEY §7.3 EnvRunner→Learner throughput).
        # Gang learners receive host batches over RPC instead.
        if self.learner_group._local is not None:
            from ray_tpu.rllib.utils.device_feed import DeviceFeed
            self._feed = DeviceFeed(self._train_queue,
                                    stop_event=self._stop_event)
        while not self._stop_event.is_set():
            try:
                if self._feed is not None:
                    batch, steps = self._feed.get(timeout=0.2)
                else:
                    with goodput.bucket("feed_stall"):
                        batch, steps = self._train_queue.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                t0 = _time.perf_counter()
                from ray_tpu.util import jax_sentinel
                with _spans.span("learner.step", steps=steps), \
                        jax_sentinel.step_region("learner.step"):
                    stats = self.learner_group.update(batch)
                if self._feed is not None:
                    self._feed.add_busy(_time.perf_counter() - t0)
            except BaseException as e:  # noqa: BLE001
                self._learner_error = e
                return
            with self._stats_lock:
                self._learner_stats = stats
                self._steps_trained += steps
                self._updates_done += 1
                self._weights_version += 1

    def _assemble_train_batch(self, staged: bool = False
                              ) -> Optional[tuple]:
        """Once train_batch_size fresh steps accumulated: drain them, mix
        in replayed fragments per replay_proportion, and return
        (batch, steps). Shared by the async and sync paths. With
        staged=True (local-learner async path) the fragments are copied
        into a reusable HostStage slot instead of a fresh concatenation
        — the DeviceFeed ships the slot's per-dtype segments fused and
        recycles it once the transfer lands."""
        cfg = self.config
        if self._fresh_steps < cfg.train_batch_size:
            return None
        frags = list(self._fresh)
        self._fresh = []
        steps = self._fresh_steps
        self._fresh_steps = 0
        for f in frags:
            self._replay.append(f)
        if cfg.replay_proportion > 0 and len(self._replay) > len(frags):
            n_replay = max(0, round(cfg.replay_proportion * len(frags)))
            for _ in range(n_replay):
                f = self._replay[self._replay_rng.integers(
                    len(self._replay))]
                frags.append(f)
                steps += f["actions"].size
        if staged:
            if self._stage is None:
                from ray_tpu.rllib.utils.device_feed import HostStage
                self._stage = HostStage(
                    slots=cfg.learner_queue_size + 4)
            return self._stage.assemble(frags, _batch_axis), steps
        return _concat_fragments(frags), steps

    def _maybe_enqueue_batch(self) -> int:
        # staged slots only work when a local learner's DeviceFeed
        # recycles them; gang learners get plain concatenated batches
        assembled = self._assemble_train_batch(
            staged=self.learner_group._local is not None)
        if assembled is None:
            return 0
        batch, steps = assembled
        # Bounded queue gives sampling backpressure on a slow learner; the
        # poll loop keeps a dead learner thread from deadlocking us here.
        while True:
            if self._learner_error is not None:
                raise self._learner_error
            try:
                self._train_queue.put((batch, steps), timeout=1.0)
                return steps
            except queue.Full:
                continue

    # ---- the training step -------------------------------------------

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        if not self.env_runners.actors:
            return self._training_step_sync()

        import ray_tpu
        from ray_tpu.util.actor_manager import FaultTolerantActorManager

        if self._learner_error is not None:
            raise self._learner_error
        self._ensure_learner_thread()
        if self._mgr is None:
            self._mgr = FaultTolerantActorManager(
                self.env_runners.actors,
                max_remote_requests_in_flight_per_actor=(
                    cfg.max_requests_in_flight_per_env_runner),
                health_probe_method="ping")
        per_request = cfg.rollout_fragment_length \
            * cfg.num_envs_per_env_runner

        # keep every healthy runner saturated (reference impala.py:692-706)
        self._mgr.foreach_actor_async(("sample", (per_request,), None))
        results = self._mgr.fetch_ready_async_reqs(timeout_seconds=2.0)
        enqueued = 0
        for r in results:
            if not r.ok:
                continue
            fragment = r.value
            self._record_episode_metrics([fragment])
            self._timesteps_total += fragment["actions"].size
            self._fresh.append(_to_timemajor(fragment))
            self._fresh_steps += fragment["actions"].size
            self._touched_ids.add(r.actor_id)
            enqueued += self._maybe_enqueue_batch()

        # targeted weight sync: only runners that contributed since the
        # last broadcast, only when the learner produced new weights
        with self._stats_lock:
            version = self._weights_version
            stats = dict(self._learner_stats)
            trained_total = self._steps_trained
        # per-iteration delta (PPO-consistent semantics); the lifetime
        # total is reported separately
        trained_delta = trained_total - self._last_reported_trained
        self._last_reported_trained = trained_total
        if version > self._synced_version and self._touched_ids and \
                self._iteration % cfg.broadcast_interval == 0:
            weights = self.learner_group.get_weights()
            actors = self._mgr.actors()
            targets = [actors[i] for i in self._touched_ids
                       if i in actors]
            ray_tpu.get([a.set_weights.remote(weights) for a in targets],
                        timeout=300)
            self._synced_version = version
            self._touched_ids.clear()
        if self._iteration % 10 == 9:
            self._mgr.probe_unhealthy_actors(timeout_seconds=2.0)
        result = {
            "learner": stats,
            "num_env_steps_trained": trained_delta,
            "num_env_steps_trained_total": trained_total,
            "num_updates_total": self._updates_done,
            "num_env_steps_enqueued": enqueued,
            "learner_queue_depth": self._train_queue.qsize(),
            "num_healthy_env_runners": self._mgr.num_healthy_actors(),
        }
        if self._feed is not None:
            result["device_feed"] = self._feed.stats()
        return result

    def _training_step_sync(self) -> Dict[str, Any]:
        """Degenerate num_env_runners=0 mode: local sampling, but still
        buffered to train_batch_size with mixin replay."""
        cfg = self.config
        fragments = self.env_runners.sample_sync(
            cfg.rollout_fragment_length * cfg.num_envs_per_env_runner)
        self._record_episode_metrics(fragments)
        stats: Dict[str, float] = {}
        trained_delta = 0
        for f in fragments:
            self._timesteps_total += f["actions"].size
            self._fresh.append(_to_timemajor(f))
            self._fresh_steps += f["actions"].size
        assembled = self._assemble_train_batch()
        if assembled is not None:
            batch, steps = assembled
            stats = self.learner_group.update(batch)
            trained_delta = steps
            with self._stats_lock:
                self._steps_trained += steps
            self.env_runners.sync_weights(self.learner_group.get_weights())
        return {"learner": stats,
                "num_env_steps_trained": trained_delta,
                "num_env_steps_trained_total": self._steps_trained}

    def stop(self) -> None:
        self._stop_event.set()
        if self._learner_thread is not None:
            self._learner_thread.join(timeout=10)
        if self._mgr is not None:
            self._mgr = None
        super().stop()
