"""Algorithm registry (reference rllib/algorithms/registry.py).

The reference registers ~34 algorithms; the TPU build ships 14 — the
north-star set (SURVEY §8.3: ppo, impala, + appo sharing IMPALA's
machinery) plus the value-learning (DQN/SimpleQ/SAC/TD3/DDPG/CQL),
on-policy (PG/A2C), derivative-free (ES) and offline (BC/MARWIL/CQL)
families — behind the same lookup surface so
`get_algorithm_class("PPO")` and Tuner-by-name work.
"""

from __future__ import annotations

from typing import Tuple, Type


def _registry():
    from ray_tpu.rllib.algorithms.a2c.a2c import A2C, A2CConfig
    from ray_tpu.rllib.algorithms.appo.appo import APPO, APPOConfig
    from ray_tpu.rllib.algorithms.cql.cql import CQL, CQLConfig
    from ray_tpu.rllib.algorithms.dqn.simple_q import (SimpleQ,
                                                       SimpleQConfig)
    from ray_tpu.rllib.algorithms.ddpg.ddpg import DDPG, DDPGConfig
    from ray_tpu.rllib.algorithms.dqn.dqn import DQN, DQNConfig
    from ray_tpu.rllib.algorithms.impala.impala import Impala, ImpalaConfig
    from ray_tpu.rllib.algorithms.es.es import ES, ESConfig
    from ray_tpu.rllib.algorithms.pg.pg import PG, PGConfig
    from ray_tpu.rllib.algorithms.marwil.marwil import (BC, MARWIL,
                                                        BCConfig,
                                                        MARWILConfig)
    from ray_tpu.rllib.algorithms.ppo.ppo import PPO, PPOConfig
    from ray_tpu.rllib.algorithms.sac.sac import SAC, SACConfig
    from ray_tpu.rllib.algorithms.td3.td3 import TD3, TD3Config
    return {
        "PPO": (PPO, PPOConfig),
        "IMPALA": (Impala, ImpalaConfig),
        "APPO": (APPO, APPOConfig),
        "DQN": (DQN, DQNConfig),
        "SAC": (SAC, SACConfig),
        "MARWIL": (MARWIL, MARWILConfig),
        "BC": (BC, BCConfig),
        "ES": (ES, ESConfig),
        "PG": (PG, PGConfig),
        "TD3": (TD3, TD3Config),
        "DDPG": (DDPG, DDPGConfig),
        "A2C": (A2C, A2CConfig),
        "SIMPLEQ": (SimpleQ, SimpleQConfig),
        "CQL": (CQL, CQLConfig),
    }


def get_algorithm_class(name: str, return_config: bool = False):
    """reference registry.py get_algorithm_class."""
    entry = _registry().get(name.upper())
    if entry is None:
        raise ValueError(
            f"unknown algorithm {name!r}; available: "
            f"{sorted(_registry())}")
    algo, config = entry
    if return_config:
        return algo, config()
    return algo


def get_algorithm_config(name: str):
    return get_algorithm_class(name, return_config=True)[1]


def registered_algorithms() -> Tuple[str, ...]:
    return tuple(sorted(_registry()))
