"""SimpleQ: plain deep Q-learning.

reference parity: rllib/algorithms/simple_q/simple_q.py — DQN stripped
of the extensions: no dueling head, no double-Q action selection, no
n-step windows, no prioritized replay; a target network refreshed on a
fixed interval and epsilon-greedy exploration. Exists as the smallest
correctness reference for the value-learning stack (the reference keeps
it for the same reason).
"""

from __future__ import annotations

from ray_tpu.rllib.algorithms.dqn.dqn import DQN, DQNConfig


class SimpleQConfig(DQNConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or SimpleQ)
        self.dueling = False
        self.double_q = False
        self.n_step = 1
        self.prioritized_replay = False
        self.lr = 5e-4
        self.train_batch_size = 32

    _FROZEN = {"dueling": False, "double_q": False, "n_step": 1,
               "prioritized_replay": False}

    def training(self, **kwargs):
        # validate BEFORE applying so a rejected call leaves the config
        # untouched; re-stating the frozen value is fine
        for key, frozen_value in self._FROZEN.items():
            if key in kwargs and kwargs[key] != frozen_value:
                raise ValueError(
                    f"SimpleQ fixes {key}={frozen_value!r}; use "
                    f"DQNConfig for the extended variant")
        return super().training(**kwargs)


class SimpleQ(DQN):
    pass
