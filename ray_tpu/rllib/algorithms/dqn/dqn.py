"""DQN: double/dueling deep Q-learning with (prioritized) replay.

reference parity: rllib/algorithms/dqn/dqn.py (DQNConfig :100 — dueling,
double_q, n_step, target_network_update_freq, replay buffer config,
epsilon schedule; training_step :510 — sample → store → replay-sample →
train → priority update → target sync) and dqn_torch_policy.py
(build_q_losses: Huber TD error, double-Q argmax from the online net).
TPU-first shape: the whole TD update (online + target forward, Huber,
Adam) is one jitted XLA program; the target network is an extra pytree
input to that program, refreshed by pointer copy in additional_update;
epsilon-greedy runs inside the env-runner's jitted forward with epsilon
threaded as a scalar array (no retrace per anneal step).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Optional, Sequence

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.catalog import _mlp_apply, _mlp_init
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import Categorical, RLModule
from ray_tpu.rllib.utils.replay_buffers import (PrioritizedReplayBuffer,
                                                ReplayBuffer)
from ray_tpu.rllib.utils.schedules import LinearSchedule


class DQNConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or DQN)
        self.lr = 5e-4
        self.train_batch_size = 32
        self.rollout_fragment_length = 4
        self.num_epochs = 1
        self.minibatch_size = None
        # DQN-specific (reference dqn.py:100 DQNConfig.training)
        self.dueling = True
        self.double_q = True
        self.n_step = 1
        self.buffer_size = 50_000
        self.prioritized_replay = False
        self.prioritized_replay_alpha = 0.6
        self.prioritized_replay_beta = 0.4
        self.num_steps_sampled_before_learning_starts = 1000
        self.target_network_update_freq = 500   # in sampled timesteps
        # trained/sampled ratio; None -> the reference's "natural value"
        # train_batch_size / rollout_fragment_length (dqn.py
        # calculate_rr_weights semantics)
        self.training_intensity = None
        # epsilon-greedy schedule (reference EpsilonGreedy exploration)
        self.initial_epsilon = 1.0
        self.final_epsilon = 0.02
        self.epsilon_timesteps = 10_000
        # distributed replay plane (APEX pattern, reference
        # apex_dqn.py): >0 moves replay out of the driver into
        # ReplayShardActors and decouples sample→store from
        # replay→train into async loops. Needs num_env_runners > 0;
        # with 0 runner actors the sync in-driver path runs regardless.
        self.num_replay_shards = 0
        self.replay_shard_capacity = None   # None -> buffer_size/shards
        self.replay_max_inflight_pushes = 4  # per shard, then shed
        self.replay_sample_inflight = 2      # pipelined pulls per shard
        self.replay_queue_depth = 4          # staged-batch queue bound
        self.max_requests_in_flight_per_env_runner = 2


class DuelingQMLPModule(RLModule):
    """Q-network MLP; dueling decomposition Q = V + A - mean(A)
    (reference dqn_torch_model.py). forward_exploration is epsilon-greedy
    over Q with epsilon read from the batch (threaded by the runner)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hiddens: Sequence[int] = (64, 64), dueling: bool = True):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hiddens = tuple(hiddens)
        self.dueling = dueling

    def init_params(self, key) -> Dict[str, Any]:
        import jax
        k1, k2, k3 = jax.random.split(key, 3)
        params = {
            "torso": _mlp_init(k1, [self.obs_dim, *self.hiddens],
                               scale_last=None),
            "adv": _mlp_init(k2, [self.hiddens[-1], self.num_actions]),
        }
        if self.dueling:
            params["val"] = _mlp_init(k3, [self.hiddens[-1], 1],
                                      scale_last=1.0)
        return params

    def forward_train(self, params, batch):
        import jax
        import jax.numpy as jnp
        h = jax.nn.relu(_mlp_apply(params["torso"], batch["obs"]))
        adv = _mlp_apply(params["adv"], h)
        if self.dueling:
            val = _mlp_apply(params["val"], h)
            q = val + adv - jnp.mean(adv, axis=-1, keepdims=True)
        else:
            q = adv
        return {"action_dist_inputs": q,
                "vf_preds": jnp.max(q, axis=-1)}

    def forward_exploration(self, params, batch, key):
        import jax
        import jax.numpy as jnp
        out = self.forward_train(params, batch)
        q = out["action_dist_inputs"]
        greedy = jnp.argmax(q, axis=-1)
        eps = batch.get("epsilon", jnp.asarray(0.0, jnp.float32))
        k1, k2 = jax.random.split(key)
        rand = jax.random.randint(k1, greedy.shape, 0, self.num_actions)
        explore = jax.random.uniform(k2, greedy.shape) < eps
        out["actions"] = jnp.where(explore, rand, greedy)
        out["action_logp"] = jnp.zeros(greedy.shape, jnp.float32)
        return out

    def action_dist(self, dist_inputs) -> Categorical:
        return Categorical(dist_inputs)


def fragment_to_transitions(fragment: Dict[str, Any], gamma: float,
                            n_step: int = 1) -> Dict[str, np.ndarray]:
    """Rollout fragment [T, N, ...] -> flat n-step transition batch.

    One transition per collected timestep (nothing dropped). A window
    starting at t accumulates gamma^j * r_{t+j} until the first episode
    end, the n-th step, or the fragment boundary — whichever comes first
    (reference assembles the same windows in
    rllib/utils/replay_buffers/utils.py). Truncation is handled exactly:
    raw (unfolded) rewards accumulate, the done flag is set only on
    *termination*, and truncated/clipped windows bootstrap from the true
    next observation (the runner's sparse final_obs) with the window's
    own discount gamma^(len) carried in the "discounts" column — so the
    target network supplies the bootstrap at *update* time, never a
    value frozen at collection time.
    """
    assert n_step >= 1
    obs = np.asarray(fragment["obs"])
    raw = np.asarray(fragment.get("raw_rewards", fragment["rewards"]),
                     np.float32)
    terms = np.asarray(fragment["terminateds"])
    truncs = np.asarray(fragment["truncateds"])
    dones = terms | truncs
    t_len, n_envs = raw.shape

    # obs after step t (autoreset where done) -> replace done rows with
    # the true final observation so truncated windows bootstrap off it
    next_seq = np.concatenate([obs[1:], fragment["last_obs"][None]],
                              axis=0).copy()
    idx = np.asarray(fragment.get("final_obs_idx",
                                  np.zeros((0, 2), np.int64)))
    if idx.size:
        next_seq[idx[:, 0], idx[:, 1]] = fragment["final_obs_vals"]

    acc_r = np.zeros((t_len, n_envs), np.float32)
    done_out = np.zeros((t_len, n_envs), np.float32)
    disc_out = np.zeros((t_len, n_envs), np.float32)
    next_t = np.zeros((t_len, n_envs), np.int64)
    open_ = np.ones((t_len, n_envs), bool)
    for j in range(n_step):
        tmax = t_len - j
        if tmax <= 0:
            break
        alive = open_[:tmax]
        acc_r[:tmax] += np.where(alive, (gamma ** j) * raw[j:], 0.0)
        closes = np.zeros((tmax, n_envs), bool)
        closes |= dones[j:]                  # episode ended at step t+j
        if j == n_step - 1:
            closes[:] = True                 # window reached n steps
        closes[tmax - 1] = True              # t+j hit the fragment end
        closes &= alive
        done_out[:tmax] = np.where(closes, terms[j:].astype(np.float32),
                                   done_out[:tmax])
        disc_out[:tmax] = np.where(closes, gamma ** (j + 1),
                                   disc_out[:tmax])
        tt = np.broadcast_to(np.arange(tmax)[:, None] + j,
                             (tmax, n_envs))
        next_t[:tmax] = np.where(closes, tt, next_t[:tmax])
        open_[:tmax] &= ~closes

    env_ix = np.broadcast_to(np.arange(n_envs), (t_len, n_envs))
    next_obs = next_seq[next_t.ravel(), env_ix.ravel()]

    def flat(x):
        return np.reshape(x, (-1,) + x.shape[2:])

    return {
        "obs": flat(obs),
        "actions": flat(np.asarray(fragment["actions"])),
        "rewards": flat(acc_r),
        "dones": flat(done_out),
        "discounts": flat(disc_out),
        "next_obs": next_obs,
    }


class DQNLearner(Learner):
    """Huber TD loss with a target-network pytree as jit input
    (reference dqn_torch_policy.py build_q_losses + QLoss)."""

    def build(self, seed: int = 0) -> None:
        super().build(seed)
        self._copy_target()

    def build_distributed(self, seed: int = 0) -> None:
        super().build_distributed(seed)
        self._copy_target()

    def _copy_target(self) -> None:
        import jax
        import jax.numpy as jnp
        with self._state_lock:
            self._target_params = jax.tree.map(jnp.copy, self._params)

    def extra_inputs(self) -> Dict[str, Any]:
        return {"target_params": self._target_params}

    def compute_loss(self, params, batch, extra):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        q_all = self.module.forward_train(
            params, {"obs": batch["obs"]})["action_dist_inputs"]
        actions = batch["actions"].astype(jnp.int32)
        q = jnp.take_along_axis(q_all, actions[:, None], axis=-1)[:, 0]

        q_next_target = self.module.forward_train(
            extra["target_params"],
            {"obs": batch["next_obs"]})["action_dist_inputs"]
        if cfg.double_q:
            q_next_online = self.module.forward_train(
                params, {"obs": batch["next_obs"]})["action_dist_inputs"]
            a_star = jnp.argmax(q_next_online, axis=-1)
            q_next = jnp.take_along_axis(
                q_next_target, a_star[:, None], axis=-1)[:, 0]
        else:
            q_next = jnp.max(q_next_target, axis=-1)

        target = batch["rewards"] + batch["discounts"] * \
            (1.0 - batch["dones"]) * q_next
        td = q - jax.lax.stop_gradient(target)
        huber = jnp.where(jnp.abs(td) < 1.0, 0.5 * td ** 2,
                          jnp.abs(td) - 0.5)
        weights = batch.get("weights")
        loss = jnp.mean(huber * weights) if weights is not None \
            else jnp.mean(huber)

        stats = {"qf_loss": loss, "mean_q": jnp.mean(q),
                 "mean_td_error": jnp.mean(jnp.abs(td)),
                 "td_error": jnp.abs(td)}
        if "batch_indexes" in batch:
            stats["td_indexes"] = batch["batch_indexes"]
        if "item_epochs" in batch:
            # staleness tickets ride to the priority update so a shard
            # can drop updates for slots recycled since the sample
            stats["td_epochs"] = batch["item_epochs"]
        return loss, stats

    def additional_update(self, *, update_target: bool = False,
                          **kw) -> Dict[str, Any]:
        if update_target:
            self._copy_target()
        return {"target_updated": bool(update_target)}

    def get_state(self) -> Dict[str, Any]:
        import jax
        state = super().get_state()
        with self._state_lock:
            state["target_params"] = jax.device_get(self._target_params)
        return state

    def set_state(self, state: Dict[str, Any]) -> None:
        super().set_state(state)
        import jax
        import jax.numpy as jnp
        with self._state_lock:
            if getattr(self, "_distributed", False):
                self._target_params = jax.tree.map(
                    self._replicate_host, state["target_params"])
            else:
                self._target_params = jax.tree.map(
                    jnp.asarray, state["target_params"])


class DQN(Algorithm):
    learner_cls = DQNLearner

    def default_module(self, observation_space, action_space):
        """Q-network instead of the actor-critic catalog default."""
        if len(observation_space.shape) != 1:
            raise NotImplementedError(
                f"DQN ships an MLP Q-net for 1-D observations; got "
                f"obs={observation_space}. Pass a custom Q RLModule "
                f"via config.rl_module(module=...) (it must expose "
                f"Q-values as action_dist_inputs and epsilon-greedy "
                f"forward_exploration, see DuelingQMLPModule).")
        return DuelingQMLPModule(
            observation_space.shape[0], action_space.n,
            self.config.model_hiddens, dueling=self.config.dueling)

    def __init__(self, config: "DQNConfig"):
        super().__init__(config)
        if config.prioritized_replay:
            self.replay_buffer: ReplayBuffer = PrioritizedReplayBuffer(
                config.buffer_size, alpha=config.prioritized_replay_alpha,
                seed=config.seed)
        else:
            self.replay_buffer = ReplayBuffer(config.buffer_size,
                                              seed=config.seed)
        self.epsilon_schedule = LinearSchedule(
            config.epsilon_timesteps, config.final_epsilon,
            config.initial_epsilon)
        self._last_target_update = 0
        # distributed replay plane (built lazily on first step)
        self._replay_group = None
        self._runner_mgr = None
        self._writer_spec_version = -1
        self._replay_thread: Optional[threading.Thread] = None
        self._replay_stop = threading.Event()
        self._replay_stats_lock = threading.Lock()
        self._replay_learner_stats: Dict[str, float] = {}
        self._replay_learner_error: Optional[BaseException] = None
        self._replay_steps_trained = 0
        self._replay_updates = 0
        self._replay_weights_version = 0
        self._replay_synced_version = 0
        self._replay_touched: set = set()
        self._replay_feed = None
        self._last_reported_trained = 0

    def _extra_state(self) -> Dict[str, Any]:
        return {"last_target_update": self._last_target_update}

    def _restore_extra_state(self, extra: Dict[str, Any]) -> None:
        self._last_target_update = extra.get(
            "last_target_update", self._last_target_update)

    # ---- hooks (SAC overrides; reference SAC extends DQN too) -------
    def _before_sample(self, stats: Dict[str, Any]) -> None:
        """Push exploration state to runners (epsilon-greedy here)."""
        eps = self.epsilon_schedule(self._timesteps_total)
        self.env_runners.set_explore_inputs({"epsilon": eps})
        stats["epsilon"] = eps

    def _training_intensity(self) -> float:
        cfg = self.config
        return (cfg.training_intensity
                if cfg.training_intensity is not None
                else cfg.train_batch_size / cfg.rollout_fragment_length)

    def _after_each_update(self) -> None:
        """Per-gradient-step target maintenance (SAC: polyak)."""

    def _maybe_update_target(self) -> None:
        """Periodic hard target sync (target_network_update_freq)."""
        if self._timesteps_total - self._last_target_update >= \
                self.config.target_network_update_freq:
            self.learner_group.additional_update(update_target=True)
            self._last_target_update = self._timesteps_total

    # ---- distributed replay plane (APEX pattern) --------------------

    def _ensure_replay_plane(self) -> None:
        if self._replay_group is not None:
            return
        cfg = self.config
        from ray_tpu.rllib.utils.replay import ReplayGroup
        from ray_tpu.util.actor_manager import FaultTolerantActorManager
        n = cfg.num_replay_shards
        capacity = cfg.replay_shard_capacity or \
            max(1, cfg.buffer_size // n)
        self._replay_group = ReplayGroup(
            n, capacity,
            prioritized=cfg.prioritized_replay,
            alpha=cfg.prioritized_replay_alpha,
            beta=cfg.prioritized_replay_beta,
            batch_size=cfg.train_batch_size,
            min_size_to_sample=max(
                cfg.train_batch_size,
                cfg.num_steps_sampled_before_learning_starts // n),
            seed=cfg.seed,
            queue_depth=cfg.replay_queue_depth,
            sample_inflight_per_shard=cfg.replay_sample_inflight)
        self._replay_group.start()
        self._runner_mgr = FaultTolerantActorManager(
            self.env_runners.actors,
            max_remote_requests_in_flight_per_actor=(
                cfg.max_requests_in_flight_per_env_runner),
            health_probe_method="ping")
        self._install_writer_spec()
        if self._replay_thread is None:
            self._replay_thread = threading.Thread(
                target=self._replay_learner_loop, daemon=True,
                name="dqn-replay-learner")
            self._replay_thread.start()

    def _install_writer_spec(self) -> None:
        """Ship the current shard handle set to every runner — called at
        startup and again whenever the group resharded (a replaced shard
        means the old handles route pushes into a dead actor)."""
        cfg = self.config
        spec = {"shards": self._replay_group.shard_handles(),
                "max_inflight_per_shard": cfg.replay_max_inflight_pushes,
                "gamma": cfg.gamma, "n_step": cfg.n_step}
        self._runner_mgr.foreach_actor(
            ("set_replay_writer", (spec,), None), timeout_seconds=60.0)
        self._writer_spec_version = self._replay_group.reshard_version

    def _replay_learner_loop(self) -> None:
        """replay→train loop: drain staged batches the ReplayGroup
        puller pipelined off the shards, update, and route TD-error
        priorities back to the issuing shard (one-way)."""
        import time as _time

        from ray_tpu._private import spans as _spans
        from ray_tpu.util import jax_sentinel

        cfg = self.config
        group = self._replay_group
        # goodput ledger for the replay learner thread: replay-sample
        # starvation is replay_stall (distinct from the on-policy
        # feed_stall — a starved replay plane has different fixes)
        from ray_tpu._private import goodput
        goodput.ledger("dqn").bind()
        if self.learner_group._local is not None:
            from ray_tpu.rllib.utils.device_feed import DeviceFeed
            self._replay_feed = DeviceFeed(group.queue,
                                           stop_event=self._replay_stop,
                                           stall_bucket="replay_stall")
        while not self._replay_stop.is_set():
            staged = None
            try:
                if self._replay_feed is not None:
                    batch, meta = self._replay_feed.get(timeout=0.2)
                else:
                    with goodput.bucket("replay_stall"):
                        staged, meta = group.queue.get(timeout=0.2)
                    batch = staged.as_dict()
            except queue.Empty:
                continue
            try:
                t0 = _time.perf_counter()
                with _spans.span("learner.step",
                                 steps=cfg.train_batch_size), \
                        jax_sentinel.step_region("learner.step"):
                    st = self.learner_group.update(
                        batch, minibatch_size=None, num_iters=1,
                        seed=(cfg.seed or 0) + self._replay_updates)
                if self._replay_feed is not None:
                    self._replay_feed.add_busy(
                        _time.perf_counter() - t0)
            except BaseException as e:  # noqa: BLE001
                self._replay_learner_error = e
                return
            finally:
                if staged is not None:
                    staged.release()
            if group.prioritized and "td_error" in st:
                group.update_priorities(
                    meta.get("shard_id"),
                    np.asarray(st["td_indexes"], np.int64),
                    np.asarray(st["td_error"], np.float64),
                    np.asarray(st["td_epochs"], np.int64)
                    if "td_epochs" in st else None)
            self._after_each_update()
            with self._replay_stats_lock:
                self._replay_learner_stats = {
                    k: float(v) for k, v in st.items()
                    if not getattr(v, "ndim", 0)}
                self._replay_steps_trained += cfg.train_batch_size
                self._replay_updates += 1
                self._replay_weights_version += 1

    def _training_step_replay_plane(self) -> Dict[str, Any]:
        """sample→store and replay→train as decoupled async loops: env
        runners push transitions straight to the replay shards (only
        metadata returns here), the group's puller keeps sample RPCs
        pipelined, and the learner thread trains off the staged queue."""
        import ray_tpu

        cfg = self.config
        if self._replay_learner_error is not None:
            raise self._replay_learner_error
        self._ensure_replay_plane()
        stats: Dict[str, Any] = {}
        self._before_sample(stats)
        per_request = cfg.rollout_fragment_length \
            * cfg.num_envs_per_env_runner
        self._runner_mgr.foreach_actor_async(
            ("sample_to_replay", (per_request,), None))
        results = self._runner_mgr.fetch_ready_async_reqs(
            timeout_seconds=2.0)
        sampled = 0
        writer_stats: Dict[str, int] = {}
        for r in results:
            if not r.ok:
                continue
            meta = r.value
            sampled += meta["steps"]
            self._record_episode_metrics([meta])
            self._replay_touched.add(r.actor_id)
            writer_stats = meta.get("writer", writer_stats)
        self._timesteps_total += sampled
        # a reshard invalidates the shard handles baked into runner
        # writers — re-ship the spec before more pushes go astray
        if self._replay_group.reshard_version != \
                self._writer_spec_version:
            self._install_writer_spec()
        with self._replay_stats_lock:
            version = self._replay_weights_version
            lstats = dict(self._replay_learner_stats)
            trained_total = self._replay_steps_trained
            updates_total = self._replay_updates
        trained_delta = trained_total - self._last_reported_trained
        self._last_reported_trained = trained_total
        if version > self._replay_synced_version and \
                self._replay_touched:
            weights = self.learner_group.get_weights()
            actors = self._runner_mgr.actors()
            targets = [actors[i] for i in self._replay_touched
                       if i in actors]
            ray_tpu.get(
                [a.set_weights.remote(weights) for a in targets],
                timeout=300)
            self._replay_synced_version = version
            self._replay_touched.clear()
        self._maybe_update_target()
        if self._iteration % 10 == 9:
            self._runner_mgr.probe_unhealthy_actors(timeout_seconds=2.0)
            self._replay_group.probe_unhealthy()
        stats.update(lstats)
        return {
            "learner": stats,
            "num_env_steps_sampled": sampled,
            "num_env_steps_trained": trained_delta,
            "num_env_steps_trained_total": trained_total,
            "num_updates_total": updates_total,
            "replay": self._replay_group.stats(),
            "replay_writer": writer_stats,
            "num_healthy_env_runners":
                self._runner_mgr.num_healthy_actors(),
            "device_feed": (self._replay_feed.stats()
                            if self._replay_feed is not None else {}),
        }

    def stop(self) -> None:
        self._replay_stop.set()
        if self._replay_thread is not None:
            self._replay_thread.join(timeout=10)
            self._replay_thread = None
        if self._replay_group is not None:
            self._replay_group.stop()
            self._replay_group = None
        super().stop()

    # ---- the shared replay loop -------------------------------------
    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        if cfg.num_replay_shards > 0 and self.env_runners.actors:
            return self._training_step_replay_plane()
        # --- explore + sample (reference dqn.py training_step) -------
        stats: Dict[str, Any] = {}
        self._before_sample(stats)
        fragments = self.env_runners.sample_sync(
            cfg.rollout_fragment_length * cfg.num_envs_per_env_runner)
        self._record_episode_metrics(fragments)
        sampled = 0
        for f in fragments:
            trans = fragment_to_transitions(f, cfg.gamma, cfg.n_step)
            self.replay_buffer.add(trans)
            sampled += f["rewards"].size
        self._timesteps_total += sampled

        # --- replay train --------------------------------------------
        if self.replay_buffer.num_added >= \
                cfg.num_steps_sampled_before_learning_starts:
            num_updates = max(1, round(
                sampled * self._training_intensity()
                / cfg.train_batch_size))
            agg: Dict[str, float] = {}
            for u in range(num_updates):
                if isinstance(self.replay_buffer, PrioritizedReplayBuffer):
                    batch = self.replay_buffer.sample(
                        cfg.train_batch_size,
                        beta=cfg.prioritized_replay_beta)
                else:
                    batch = self.replay_buffer.sample(cfg.train_batch_size)
                st = self.learner_group.update(
                    batch, minibatch_size=None, num_iters=1,
                    seed=cfg.seed + self._iteration * 1000 + u)
                if isinstance(self.replay_buffer, PrioritizedReplayBuffer) \
                        and "td_error" in st:
                    self.replay_buffer.update_priorities(
                        np.asarray(st["td_indexes"], np.int64),
                        np.asarray(st["td_error"]))
                self._after_each_update()
                for k, v in st.items():
                    if not getattr(v, "ndim", 0):
                        agg[k] = agg.get(k, 0.0) + float(v)
            stats.update({k: v / num_updates for k, v in agg.items()})
            stats["num_updates"] = num_updates
            self._maybe_update_target()
            # --- weight sync -----------------------------------------
            self.env_runners.sync_weights(self.learner_group.get_weights())
        return {"learner": stats, "num_env_steps_sampled": sampled,
                "replay_buffer_size": len(self.replay_buffer)}
