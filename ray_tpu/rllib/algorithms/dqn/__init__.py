from ray_tpu.rllib.algorithms.dqn.dqn import (DQN, DQNConfig, DQNLearner,
                                              DuelingQMLPModule)

__all__ = ["DQN", "DQNConfig", "DQNLearner", "DuelingQMLPModule"]
