"""PPO: clipped surrogate objective with GAE.

reference parity: rllib/algorithms/ppo/ppo.py:61 (PPOConfig), :397 (PPO),
training_step :423-530 — sample → GAE postprocess → standardize
advantages → LearnerGroup.update(minibatch SGD) → KL-coeff
additional_update (ppo.py:366) → sync_weights (:522-530). Loss per
ppo_learner/ppo_torch_policy: clip surrogate + clipped VF loss +
entropy bonus + adaptive KL penalty. Here the whole minibatch update is
one jitted XLA program (core/learner.py).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner, MultiAgentLearnerMixin
from ray_tpu.rllib.utils.postprocessing import (postprocess_fragment,
                                                standardize)


class PPOConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or PPO)
        self.lr = 5e-5
        self.clip_param = 0.3
        self.vf_clip_param = 10.0
        self.entropy_coeff = 0.0
        self.vf_loss_coeff = 1.0
        self.kl_coeff = 0.2
        self.kl_target = 0.01
        self.use_kl_loss = True
        self.num_epochs = 30
        self.minibatch_size = 128
        self.train_batch_size = 4000


class PPOLearner(Learner):
    """reference ppo_learner.py:39 + ppo.py:366 KL update."""

    def extra_inputs(self) -> Dict[str, Any]:
        return {"kl_coeff": self.curr_kl_coeff}

    def compute_loss(self, params, batch, extra):
        return self._module_loss(self.module, params, batch, extra)

    def _module_loss(self, module, params, batch, extra):
        import jax.numpy as jnp

        out = module.forward_train(params, batch)
        dist = module.action_dist(out["action_dist_inputs"])
        logp = dist.logp(batch["actions"])
        logp_ratio = jnp.exp(logp - batch["action_logp"])
        adv = batch["advantages"]

        clip = self.config.clip_param
        surrogate = jnp.minimum(
            adv * logp_ratio,
            adv * jnp.clip(logp_ratio, 1 - clip, 1 + clip))

        # clipped value loss (reference ppo_torch_policy.py loss)
        vf = out["vf_preds"]
        vf_clipped = batch["vf_preds"] + jnp.clip(
            vf - batch["vf_preds"], -self.config.vf_clip_param,
            self.config.vf_clip_param)
        vf_loss = jnp.maximum(
            (vf - batch["value_targets"]) ** 2,
            (vf_clipped - batch["value_targets"]) ** 2)
        vf_loss = jnp.clip(vf_loss, 0, self.config.vf_clip_param ** 2)

        entropy = dist.entropy()
        # approximate KL(old || new) for the penalty + adaptation signal
        kl = batch["action_logp"] - logp
        mean_kl = jnp.mean(kl)

        loss = (-jnp.mean(surrogate)
                + self.config.vf_loss_coeff * jnp.mean(vf_loss)
                - self.config.entropy_coeff * jnp.mean(entropy))
        if self.config.use_kl_loss:
            loss = loss + extra["kl_coeff"] * mean_kl

        return loss, {
            "policy_loss": -jnp.mean(surrogate),
            "vf_loss": jnp.mean(vf_loss),
            "entropy": jnp.mean(entropy),
            "mean_kl_loss": mean_kl,
        }

    def additional_update(self, *, mean_kl: float) -> Dict[str, Any]:
        """Adaptive KL coefficient (reference ppo.py:366
        update_kl / ppo_learner additional_update_for_module)."""
        if mean_kl > 2.0 * self.config.kl_target:
            self.curr_kl_coeff *= 1.5
        elif mean_kl < 0.5 * self.config.kl_target:
            self.curr_kl_coeff *= 0.5
        return {"curr_kl_coeff": self.curr_kl_coeff}


class MultiAgentPPOLearner(MultiAgentLearnerMixin, PPOLearner):
    """Per-module PPO losses summed into one jitted update (reference
    marl_module.py:40 + learner.py compute_loss over a MultiAgentBatch).
    The KL coefficient adapts on the cross-module mean (shared
    coefficient; per-module KLs are reported individually)."""

    def compute_loss(self, params, batch, extra):
        total = 0.0
        stats: Dict[str, Any] = {}
        kls = []
        for mid in self.module.module_ids:
            loss_m, st = self._module_loss(
                self.module[mid], params[mid], batch[mid], extra)
            total = total + loss_m
            kls.append(st["mean_kl_loss"])
            for k, v in st.items():
                stats[f"{mid}/{k}"] = v
        stats["mean_kl_loss"] = sum(kls) / len(kls)
        return total, stats


class PPO(Algorithm):
    learner_cls = PPOLearner
    ma_learner_cls = MultiAgentPPOLearner

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        # --- sample phase (ppo.py:428-460) ---------------------------
        per_runner = max(
            cfg.rollout_fragment_length * cfg.num_envs_per_env_runner,
            cfg.train_batch_size // len(self.env_runners))
        fragments = self.env_runners.sample_sync(per_runner)
        self._record_episode_metrics(fragments)

        processed = [postprocess_fragment(f, cfg.gamma, cfg.lambda_)
                     for f in fragments]
        if cfg.policies:
            # MultiAgentBatch: split flat rows by the lane→module routing
            # ([T, N] flatten means row t*N+lane, so the per-row module
            # is lane_module tiled T times); advantages standardize
            # per module (each module is its own optimization problem).
            parts: Dict[str, list] = {}
            for f, p in zip(fragments, processed):
                t_len = f["actions"].shape[0]
                order = f["module_order"]
                row_mod = np.tile(f["lane_module"], t_len)
                for i, mid in enumerate(order):
                    rows = row_mod == i
                    parts.setdefault(mid, []).append(
                        {k: v[rows] for k, v in p.items()})
            batch = {mid: {k: np.concatenate([pp[k] for pp in ps])
                           for k in ps[0]}
                     for mid, ps in parts.items()}
            n_rows = sum(len(b["obs"]) for b in batch.values())
            self._timesteps_total += n_rows
            for b in batch.values():
                b["advantages"] = standardize(b["advantages"])
            stats = self.learner_group.update(
                batch, minibatch_size=cfg.minibatch_size,
                num_iters=cfg.num_epochs, seed=cfg.seed + self._iteration)
            extra = self.learner_group.additional_update(
                mean_kl=stats.get("mean_kl_loss", 0.0))
            stats.update(extra)
            self.env_runners.sync_weights(
                self.learner_group.get_weights())
            return {"learner": stats, "num_env_steps_trained": n_rows}
        batch = {k: np.concatenate([p[k] for p in processed])
                 for k in processed[0]}
        self._timesteps_total += len(batch["obs"])
        batch["advantages"] = standardize(batch["advantages"])

        # --- learn phase (ppo.py:487-491) ----------------------------
        stats = self.learner_group.update(
            batch, minibatch_size=cfg.minibatch_size,
            num_iters=cfg.num_epochs, seed=cfg.seed + self._iteration)

        # --- additional updates (KL coeff, ppo.py:366) ---------------
        extra = self.learner_group.additional_update(
            mean_kl=stats.get("mean_kl_loss", 0.0))
        stats.update(extra)

        # --- sync phase (ppo.py:522-530) -----------------------------
        self.env_runners.sync_weights(self.learner_group.get_weights())
        return {"learner": stats,
                "num_env_steps_trained": len(batch["obs"])}
