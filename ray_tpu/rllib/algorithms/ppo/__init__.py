from ray_tpu.rllib.algorithms.ppo.ppo import PPO, PPOConfig, PPOLearner  # noqa: F401
