from ray_tpu.rllib.algorithms.pg.pg import PG, PGConfig, PGLearner

__all__ = ["PG", "PGConfig", "PGLearner"]
