"""PG: vanilla policy gradient (REINFORCE with a value baseline).

reference parity: rllib/algorithms/pg/pg.py + pg_torch_policy.py —
loss = -mean(logp(a) * advantage), one pass per batch, no clipping or
KL machinery; advantages come from the standard GAE postprocessing
(lambda=1 gives pure Monte-Carlo returns-to-go minus baseline). The
simplest on-policy baseline in the registry, useful as a correctness
reference for the fancier algorithms.
"""

from __future__ import annotations

from ray_tpu.rllib.algorithms.ppo.ppo import PPO, PPOConfig
from ray_tpu.rllib.core.learner import Learner


class PGConfig(PPOConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or PG)
        self.lr = 4e-3
        self.train_batch_size = 2000
        self.minibatch_size = None   # single full-batch pass
        self.num_epochs = 1
        self.lambda_ = 1.0           # Monte-Carlo returns-to-go
        self.use_kl_loss = False     # PPO-only machinery, inert here


class PGLearner(Learner):
    def compute_loss(self, params, batch, extra):
        import jax.numpy as jnp

        out = self.module.forward_train(params, batch)
        dist = self.module.action_dist(out["action_dist_inputs"])
        logp = dist.logp(batch["actions"])
        policy_loss = -jnp.mean(logp * batch["advantages"])
        vf = out["vf_preds"]
        vf_loss = jnp.mean((vf - batch["value_targets"]) ** 2)
        entropy = jnp.mean(dist.entropy())
        loss = (policy_loss
                + self.config.vf_loss_coeff * vf_loss
                - self.config.entropy_coeff * entropy)
        return loss, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                      "entropy": entropy}


class PG(PPO):
    """Reuses PPO's on-policy training_step verbatim (sample →
    postprocess → standardize → update → sync); the KL additional_update
    no-ops because PGLearner inherits the base's empty
    additional_update."""

    learner_cls = PGLearner
