"""TD3: twin-delayed deep deterministic policy gradient.

reference parity: rllib/algorithms/td3/td3.py (TD3Config — twin Q,
target policy smoothing with clipped noise, delayed policy updates,
gaussian exploration; built on the DDPG policy ddpg_torch_policy.py).
TPU-first shape like SAC: critic + (gated) actor losses fuse into one
jitted update; the policy-delay gate rides in as a 0/1 scalar so the
program never retraces; targets (policy + twin Q) polyak-update in a
tiny second program. Exploration noise scale threads into the runner's
jitted forward like DQN's epsilon.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import numpy as np

from ray_tpu.rllib.algorithms.dqn.dqn import DQN, DQNConfig
from ray_tpu.rllib.core.catalog import _mlp_apply, _mlp_init
from ray_tpu.rllib.core.rl_module import RLModule
from ray_tpu.rllib.core.target_learner import (ContinuousReplayAlgoMixin,
                                               PolyakTargetLearner)


class TD3Config(DQNConfig):
    """Shares DQN's replay-loop knobs; DQN-only knobs (dueling,
    double_q, epsilon_*) are inert."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or TD3)
        self.lr = 1e-3
        self.train_batch_size = 100
        self.rollout_fragment_length = 1
        self.tau = 0.005
        self.policy_delay = 2
        self.target_noise = 0.2          # smoothing noise stddev
        self.target_noise_clip = 0.5
        self.exploration_noise = 0.1     # of the action range
        self.num_steps_sampled_before_learning_starts = 1500
        self.initial_epsilon = self.final_epsilon = 0.0


class DeterministicModule(RLModule):
    """mu(s) policy + twin Q(s, a) critics (reference
    ddpg_torch_model.py). Exploration adds gaussian action noise scaled
    by batch["noise_scale"] (threaded by the runner)."""

    def __init__(self, obs_dim: int, act_dim: int, low, high,
                 hiddens: Sequence[int] = (256, 256)):
        self.obs_dim = obs_dim
        self.act_dim = act_dim
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)
        self.hiddens = tuple(hiddens)

    def init_params(self, key) -> Dict[str, Any]:
        import jax
        kp, k1, k2 = jax.random.split(key, 3)
        pi_sizes = [self.obs_dim, *self.hiddens, self.act_dim]
        q_sizes = [self.obs_dim + self.act_dim, *self.hiddens, 1]
        return {"pi": _mlp_init(kp, pi_sizes),
                "q1": _mlp_init(k1, q_sizes, scale_last=1.0),
                "q2": _mlp_init(k2, q_sizes, scale_last=1.0)}

    def _scale(self):
        return (self.high - self.low) / 2.0, (self.high + self.low) / 2.0

    def mu(self, params, obs):
        import jax.numpy as jnp
        scale, mid = self._scale()
        return jnp.tanh(_mlp_apply(params["pi"], obs)) * scale + mid

    def q_values(self, params, obs, actions):
        import jax.numpy as jnp
        x = jnp.concatenate([obs, actions.astype(jnp.float32)], axis=-1)
        return (_mlp_apply(params["q1"], x)[..., 0],
                _mlp_apply(params["q2"], x)[..., 0])

    def forward_train(self, params, batch):
        import jax.numpy as jnp
        a = self.mu(params, batch["obs"])
        return {"action_dist_inputs": a,
                "vf_preds": jnp.zeros(a.shape[:-1], jnp.float32)}

    def forward_exploration(self, params, batch, key):
        import jax
        import jax.numpy as jnp
        out = self.forward_train(params, batch)
        a = out["action_dist_inputs"]
        scale, _ = self._scale()
        noise_scale = batch.get("noise_scale",
                                jnp.asarray(0.0, jnp.float32))
        noise = jax.random.normal(key, a.shape) * scale * noise_scale
        out["actions"] = jnp.clip(a + noise, self.low, self.high)
        out["action_logp"] = jnp.zeros(a.shape[:-1], jnp.float32)
        return out

    def forward_inference(self, params, batch):
        out = self.forward_train(params, batch)
        out["actions"] = out["action_dist_inputs"]
        return out


class TD3Learner(PolyakTargetLearner):
    """One jitted update: twin-critic TD loss against a smoothed target
    action, plus the deterministic policy-gradient term gated by the
    policy-delay scalar (reference ddpg_torch_policy.py
    build_ddpg_losses + TD3's smoothing/delay). Target scaffolding
    comes from PolyakTargetLearner (whole param tree)."""

    target_keys = None  # target the full tree: pi + q1 + q2
    rng_salt = 311

    def _post_build(self, seed: int) -> None:
        super()._post_build(seed)
        self._updates = 0

    def extra_inputs(self) -> Dict[str, Any]:
        import jax.numpy as jnp
        extra = super().extra_inputs()
        self._updates += 1
        gate = 1.0 if self._updates % self.config.policy_delay == 0 \
            else 0.0
        extra["policy_gate"] = jnp.asarray(gate, jnp.float32)
        return extra

    def postprocess_updates(self, updates, extra):
        """Actor params move ONLY on delayed steps (TD3's invariant) —
        zeroing the loss alone would leave Adam momentum walking the
        policy every step. Deliberate deviation from the reference's
        separate actor optimizer: the shared Adam's pi moments still
        decay during gated steps (slightly smaller effective momentum),
        which keeps the whole update one fused XLA program."""
        import jax
        updates = dict(updates)
        updates["pi"] = jax.tree.map(
            lambda u: u * extra["policy_gate"], updates["pi"])
        return updates

    def compute_loss(self, params, batch, extra):
        import jax
        import jax.numpy as jnp
        from jax import lax

        m: DeterministicModule = self.module
        cfg = self.config
        scale = (m.high - m.low) / 2.0

        # ---- smoothed target action (TD3's trick #3) ----------------
        a_next = m.mu(extra["target"], batch["next_obs"])
        noise = jnp.clip(
            jax.random.normal(extra["rng"], a_next.shape)
            * cfg.target_noise * scale,
            -cfg.target_noise_clip * scale,
            cfg.target_noise_clip * scale)
        a_next = jnp.clip(a_next + noise, m.low, m.high)

        tq1, tq2 = m.q_values(extra["target"], batch["next_obs"],
                              a_next)
        q_next = jnp.minimum(tq1, tq2)
        target = lax.stop_gradient(
            batch["rewards"] + batch["discounts"]
            * (1.0 - batch["dones"]) * q_next)

        q1, q2 = m.q_values(params, batch["obs"], batch["actions"])
        w = batch.get("weights")
        td_sq = 0.5 * ((q1 - target) ** 2 + (q2 - target) ** 2)
        critic_loss = jnp.mean(td_sq * w) if w is not None \
            else jnp.mean(td_sq)

        # ---- delayed deterministic policy gradient ------------------
        q_sg = {"q1": jax.tree.map(lax.stop_gradient, params["q1"]),
                "q2": jax.tree.map(lax.stop_gradient, params["q2"])}
        pi_a = m.mu(params, batch["obs"])
        q_pi, _ = m.q_values(q_sg, batch["obs"], pi_a)
        actor_loss = -jnp.mean(q_pi)

        loss = critic_loss + extra["policy_gate"] * actor_loss
        stats = {"critic_loss": critic_loss, "actor_loss": actor_loss,
                 "mean_q": jnp.mean(jnp.minimum(q1, q2)),
                 "td_error": 0.5 * (jnp.abs(q1 - target)
                                    + jnp.abs(q2 - target))}
        if "batch_indexes" in batch:
            stats["td_indexes"] = batch["batch_indexes"]
        return loss, stats

    def get_state(self) -> Dict[str, Any]:
        state = super().get_state()
        state["updates"] = self._updates
        return state

    def set_state(self, state: Dict[str, Any]) -> None:
        super().set_state(state)
        self._updates = state.get("updates", self._updates)


class TD3(ContinuousReplayAlgoMixin, DQN):
    """DQN's replay loop with TD3 hooks: gaussian action noise instead
    of epsilon, polyak targets after every update."""

    learner_cls = TD3Learner

    def default_module(self, observation_space, action_space):
        if len(observation_space.shape) != 1 or \
                not hasattr(action_space, "low"):
            raise NotImplementedError(
                f"TD3 ships a deterministic MLP for 1-D obs and Box "
                f"actions; got obs={observation_space} "
                f"act={action_space}.")
        return DeterministicModule(
            observation_space.shape[0], action_space.shape[0],
            action_space.low, action_space.high,
            self.config.model_hiddens)

    def _before_sample(self, stats: Dict[str, Any]) -> None:
        self.env_runners.set_explore_inputs(
            {"noise_scale": self.config.exploration_noise})
        stats["exploration_noise"] = self.config.exploration_noise
