from ray_tpu.rllib.algorithms.td3.td3 import (TD3, DeterministicModule,
                                              TD3Config, TD3Learner)

__all__ = ["TD3", "TD3Config", "TD3Learner", "DeterministicModule"]
