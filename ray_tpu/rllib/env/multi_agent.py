"""Multi-agent environments with shared-policy training.

reference parity: rllib/env/multi_agent_env.py — MultiAgentEnv speaks
dicts keyed by agent id: reset() -> ({agent: obs}, infos);
step({agent: action}) -> (obs, rewards, terminateds, truncateds, infos)
with the special "__all__" key ending the episode; make_multi_agent
(:449) turns any registered single-agent env into an N-agent copy for
testing. Scope here: a fixed agent roster with homogeneous spaces and a
SHARED policy — each agent becomes one lane of the standard [T, N]
fragment layout, so PPO/IMPALA/GAE run unchanged (the reference's
per-policy mapping is future work; shared policy is its default too).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np

from ray_tpu.rllib.env.base import Env, make_env


class MultiAgentEnv:
    """Dict-keyed env protocol (reference MultiAgentEnv)."""

    # fixed roster; subclasses set in __init__
    agents: List[str] = []
    observation_space = None   # shared (homogeneous) per-agent space
    action_space = None

    def reset(self, seed: Optional[int] = None
              ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        raise NotImplementedError

    def step(self, actions: Dict[str, Any]):
        """-> (obs, rewards, terminateds, truncateds, infos); the
        terminateds/truncateds dicts carry per-agent flags plus
        "__all__" for episode end."""
        raise NotImplementedError

    def close(self) -> None:
        pass


def make_multi_agent(env_name_or_creator: Union[str, Callable],
                     ) -> Callable[[Dict[str, Any]], "MultiAgentEnv"]:
    """reference make_multi_agent: N independent copies of a
    single-agent env exposed as agents "agent_0..agent_{n-1}"; config
    key num_agents picks N."""

    class _CopyMultiAgent(MultiAgentEnv):
        def __init__(self, config: Optional[Dict[str, Any]] = None):
            config = dict(config or {})
            n = int(config.pop("num_agents", 2))
            if callable(env_name_or_creator):
                self._envs = [env_name_or_creator(config)
                              for _ in range(n)]
            else:
                self._envs = [make_env(env_name_or_creator, config)
                              for _ in range(n)]
            self.agents = [f"agent_{i}" for i in range(n)]
            self.observation_space = self._envs[0].observation_space
            self.action_space = self._envs[0].action_space
            self._reset_count = 0

        def reset(self, seed: Optional[int] = None):
            obs, infos = {}, {}
            for i, (a, e) in enumerate(zip(self.agents, self._envs)):
                o, info = e.reset(None if seed is None else seed + i)
                obs[a], infos[a] = o, info
            return obs, infos

        def step(self, actions: Dict[str, Any]):
            # independent sub-envs auto-reset per agent (no shared
            # state), so lanes never idle waiting for "__all__" — the
            # reference's copy env behaves the same way
            obs, rews, terms, truncs, infos = {}, {}, {}, {}, {}
            for i, (a, e) in enumerate(zip(self.agents, self._envs)):
                o, r, te, tr, info = e.step(actions[a])
                rews[a] = r
                terms[a], truncs[a] = te, tr
                if te or tr:
                    self._reset_count += 1
                    new_o, _ = e.reset(self._reset_count * 7919 + i)
                    obs[a] = new_o
                    infos[a] = {"final_obs": o}
                else:
                    obs[a], infos[a] = o, info
            terms["__all__"] = False
            truncs["__all__"] = False
            return obs, rews, terms, truncs, infos

        def close(self):
            for e in self._envs:
                e.close()

    return _CopyMultiAgent


class MultiAgentVectorAdapter:
    """Adapt fixed-roster MultiAgentEnvs to the SyncVectorEnv surface:
    each (env, agent) pair is one lane of the [T, N] fragment layout.

    Episode ends via "__all__" reset every lane together; lanes that
    had no per-agent flag get the boundary flag synthesized so GAE and
    episode metrics see the episode end. Agents that end BEFORE
    "__all__" idle their lane (frozen obs, zero reward, no further done
    flags) until the joint reset; those filler rows DO enter training
    with small (gamma-1)*V advantages — a known bias of the lane
    layout. Prefer envs whose agents end together or auto-reset per
    agent (make_multi_agent does) — the reference avoids this by
    collecting per-agent episode objects instead of lanes.
    """

    def __init__(self, env_fns: List[Callable[[], MultiAgentEnv]]):
        self.envs = [fn() for fn in env_fns]
        for e in self.envs:
            if not isinstance(e, MultiAgentEnv):
                raise TypeError(f"expected MultiAgentEnv, got {type(e)}")
        self.agents_per_env = [list(e.agents) for e in self.envs]
        self.num_envs = sum(len(a) for a in self.agents_per_env)
        self.observation_space = self.envs[0].observation_space
        self.action_space = self.envs[0].action_space
        self._last_obs: List[Any] = [None] * self.num_envs
        self._ended: List[bool] = [False] * self.num_envs
        self._seed_counter = 0

    def _lanes(self):
        lane = 0
        for ei, agents in enumerate(self.agents_per_env):
            for a in agents:
                yield lane, ei, a
                lane += 1

    def reset(self, seed: Optional[int] = None):
        infos: List[Dict[str, Any]] = []
        for ei, e in enumerate(self.envs):
            obs, info = e.reset(None if seed is None else seed + ei)
            infos.extend(info.get(a, {}) for a in self.agents_per_env[ei])
            base = sum(len(x) for x in self.agents_per_env[:ei])
            for j, a in enumerate(self.agents_per_env[ei]):
                self._last_obs[base + j] = obs[a]
                self._ended[base + j] = False
        return np.stack(self._last_obs), infos

    def step(self, actions):
        rewards = np.zeros(self.num_envs, np.float32)
        terms = np.zeros(self.num_envs, bool)
        truncs = np.zeros(self.num_envs, bool)
        infos: List[Dict[str, Any]] = [{} for _ in range(self.num_envs)]
        final_obs: List[Any] = [None] * self.num_envs

        for ei, e in enumerate(self.envs):
            agents = self.agents_per_env[ei]
            base = sum(len(x) for x in self.agents_per_env[:ei])
            act = {a: actions[base + j]
                   for j, a in enumerate(agents)
                   if not self._ended[base + j]}
            obs, rews, te, tr, info = e.step(act)
            all_done = te.get("__all__", False) or \
                tr.get("__all__", False)
            env_term = te.get("__all__", False)
            for j, a in enumerate(agents):
                lane = base + j
                lane_info = info.get(a, {})
                rewards[lane] = float(rews.get(a, 0.0))
                if not self._ended[lane]:
                    terms[lane] = bool(te.get(a, False))
                    truncs[lane] = bool(tr.get(a, False))
                    if all_done and not (terms[lane] or truncs[lane]):
                        # episode ended via "__all__" only: the lane
                        # still needs its boundary flag, else GAE would
                        # bridge into the next episode and episode
                        # metrics would never complete
                        terms[lane] = env_term
                        truncs[lane] = not env_term
                    if terms[lane] or truncs[lane]:
                        # true final obs, most-authoritative first:
                        # explicit info["final_obs"] (autoresetting
                        # envs), the done-step obs[a] (plain envs), or
                        # the pre-step obs as a last resort (envs that
                        # drop done agents without reporting — the
                        # bootstrap is then one step stale)
                        if "final_obs" in lane_info:
                            final_obs[lane] = lane_info["final_obs"]
                        elif a in obs:
                            final_obs[lane] = obs[a]
                        else:
                            final_obs[lane] = self._last_obs[lane]
                        if a not in obs:
                            # no replacement obs: this agent idles
                            # until the episode's "__all__"
                            self._ended[lane] = True
                if a in obs:
                    self._last_obs[lane] = obs[a]
                infos[lane] = lane_info
            if all_done:
                self._seed_counter += 1
                new_obs, _ = e.reset(self._seed_counter * 977 + ei)
                for j, a in enumerate(agents):
                    lane = base + j
                    self._last_obs[lane] = new_obs[a]
                    self._ended[lane] = False
        return (np.stack(self._last_obs), rewards, terms, truncs,
                infos, final_obs)

    def close(self) -> None:
        for e in self.envs:
            e.close()
