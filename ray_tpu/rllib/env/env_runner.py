"""EnvRunner: CPU rollout workers shipping trajectories.

reference parity: rllib/env/env_runner.py:15 (EnvRunner ABC) +
single_agent_env_runner.py:34,99,139,312 — vector envs stepped with
module.forward_exploration (:227), episodes returned to the driver
through the object store. Runners are plain classes here; the Algorithm
wraps them in actors (`ray_tpu.remote`) for num_env_runners > 0 exactly
like WorkerSet does (evaluation/worker_set.py:82).

The policy forward runs jitted on the runner's CPU jax; weights arrive
as numpy pytrees via set_weights (broadcast from the Learner over the
object store — device arrays never transit it, SURVEY.md §5.8).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.core.rl_module import RLModule
from ray_tpu.rllib.env.base import make_env
from ray_tpu.rllib.env.vector import SyncVectorEnv


class SingleAgentEnvRunner:
    def __init__(self, env_name: str, module: RLModule,
                 env_config: Optional[Dict[str, Any]] = None,
                 num_envs: int = 1, seed: Optional[int] = None,
                 worker_index: int = 0, gamma: float = 0.99,
                 policy_mapping_fn=None,
                 env_connectors: Optional[list] = None,
                 action_connectors: Optional[list] = None):
        import jax
        # Runners act on CPU regardless of the driver platform. Actor
        # runners (worker_index > 0) run in their own worker process and
        # pin the whole process to CPU so they never claim the TPU. The
        # driver-local runner (worker_index == 0) must NOT re-pin the
        # process — the Learner in the same process may be jitting to the
        # real chip (BASELINE north-star config #1) — so it routes its
        # forwards to the host CPU device via jax.default_device instead.
        if worker_index > 0:
            jax.config.update("jax_platforms", "cpu")
            self._cpu_device = None
        else:
            try:
                self._cpu_device = jax.devices("cpu")[0]
            except RuntimeError:
                self._cpu_device = None

        from ray_tpu.rllib.env.multi_agent import (MultiAgentEnv,
                                                   MultiAgentVectorAdapter)
        # the probe (type dispatch) becomes the first vector member so
        # its construction isn't wasted
        probe = make_env(env_name, env_config)
        env_fns = [lambda: probe] + [
            functools.partial(make_env, env_name, env_config)
            for _ in range(num_envs - 1)]
        if isinstance(probe, MultiAgentEnv):
            # shared policy: each (env, agent) pair is one vector lane
            self.env = MultiAgentVectorAdapter(env_fns)
        else:
            self.env = SyncVectorEnv(env_fns)
        self.module = module
        self.worker_index = worker_index
        self.gamma = gamma
        # The PRNG key must live on the CPU: a TPU-committed key would
        # drag every jitted forward (committed inputs win over
        # jax.default_device) onto the chip, one dispatch per env step.
        with self._on_cpu():
            self._key = jax.random.PRNGKey(
                (seed if seed is not None else 0) * 10007 + worker_index)
        self.params = None

        # Exploration state (epsilon etc.) threads into the jitted
        # forward as scalar arrays in the batch dict — value changes
        # don't retrace (reference: exploration objects own this state,
        # rllib/utils/exploration/epsilon_greedy.py).
        self._explore_inputs: Dict[str, np.ndarray] = {}
        from ray_tpu.rllib.core.marl_module import MultiAgentRLModule
        self._ma = isinstance(module, MultiAgentRLModule)
        if self._ma:
            # Per-agent policies (reference marl_module.py:40 +
            # policy_mapping_fn): every (env, agent) lane is routed to a
            # fixed module; per-step inference is one jitted forward per
            # module over that module's lanes, scattered back.
            if not isinstance(probe, MultiAgentEnv):
                raise ValueError(
                    "multi_agent policies need a MultiAgentEnv")
            if policy_mapping_fn is None:
                raise ValueError(
                    "MultiAgentRLModule needs a policy_mapping_fn")
            lane_agents = [a for agents in self.env.agents_per_env
                           for a in agents]
            self._lane_module_ids = [policy_mapping_fn(a)
                                     for a in lane_agents]
            unknown = set(self._lane_module_ids) - set(module.modules)
            if unknown:
                raise ValueError(
                    f"policy_mapping_fn produced unknown module ids "
                    f"{sorted(unknown)}")
            self._module_order = sorted(set(self._lane_module_ids))
            self._lanes_by_module = {
                mid: np.array([i for i, m in
                               enumerate(self._lane_module_ids)
                               if m == mid], np.int64)
                for mid in self._module_order}
            self._explore_m = {}
            self._value_m = {}
            for mid in self._module_order:
                mod = module.modules[mid]
                self._explore_m[mid] = jax.jit(
                    lambda p, obs, k, extra, _m=mod:
                    _m.forward_exploration(p, {"obs": obs, **extra}, k))
                self._value_m[mid] = jax.jit(
                    lambda p, obs, _m=mod:
                    _m.forward_train(p, {"obs": obs})["vf_preds"])
        else:
            self._explore = jax.jit(
                lambda p, obs, k, extra: module.forward_exploration(
                    p, {"obs": obs, **extra}, k))
            self._value_only = jax.jit(
                lambda p, obs: module.forward_train(
                    p, {"obs": obs})["vf_preds"])

        # connector pipelines (reference connectors/): vectorized
        # obs/reward + action transforms between the env and the module
        from ray_tpu.rllib.connectors import ConnectorPipeline
        self._env_pipeline = ConnectorPipeline(env_connectors) \
            if env_connectors else None
        self._action_connectors = list(action_connectors or [])

        base_seed = None if seed is None else seed + worker_index * 1000
        self._obs, _ = self.env.reset(base_seed)
        if self._env_pipeline is not None:
            self._obs = self._env_pipeline.on_reset(self._obs)
        # per-env running episode returns/lengths for metrics
        self._ep_ret = np.zeros(self.env.num_envs, np.float64)
        self._ep_len = np.zeros(self.env.num_envs, np.int64)
        self._completed: List[Dict[str, float]] = []

    def _on_cpu(self):
        """Context placing jitted forwards on the host CPU device (no-op
        for actor runners, whose whole process is already pinned)."""
        import contextlib

        import jax
        if self._cpu_device is None:
            return contextlib.nullcontext()
        return jax.default_device(self._cpu_device)

    def _forward_explore(self, obs, key):
        """Batched stochastic forward -> (actions, logp, vf_preds) as
        numpy rows aligned with the vector lanes. Multi-agent modules
        run one jitted forward per module over its lanes and scatter."""
        import jax

        with self._on_cpu():
            if not self._ma:
                out = self._explore(self.params, obs, key,
                                    self._explore_inputs)
                # one forcing point instead of three per-field syncs:
                # device_get batches the reads into a single blocking
                # transfer per sampled step
                return jax.device_get((out["actions"],
                                       out["action_logp"],
                                       out["vf_preds"]))
            n = obs.shape[0]
            keys = jax.random.split(key, len(self._module_order))
            actions = None
            logp = np.zeros(n, np.float32)
            vf = np.zeros(n, np.float32)
            for k, mid in zip(keys, self._module_order):
                rows = self._lanes_by_module[mid]
                out = self._explore_m[mid](self.params[mid], obs[rows],
                                           k, self._explore_inputs)
                # single forcing point per module (not per field)
                a, lp, v = jax.device_get((out["actions"],
                                           out["action_logp"],
                                           out["vf_preds"]))
                if actions is None:
                    actions = np.zeros((n,) + a.shape[1:], a.dtype)
                actions[rows] = a
                logp[rows] = lp
                vf[rows] = v
            return actions, logp, vf

    def _forward_value(self, obs, lanes=None):
        """V(obs) rows; `lanes` maps each row to its vector lane (for
        module routing when rows are a subset, e.g. truncation
        bootstraps). Defaults to row i == lane i."""
        import jax

        with self._on_cpu():
            if not self._ma:
                # device_get, not np.asarray: the sanctioned forcing
                # point for the per-step bootstrap read
                return jax.device_get(self._value_only(self.params, obs))
            if lanes is None:
                lanes = np.arange(obs.shape[0])
            vf = np.zeros(obs.shape[0], np.float32)
            mods = [self._lane_module_ids[int(ln)] for ln in lanes]
            for mid in self._module_order:
                rows = np.array([i for i, m in enumerate(mods)
                                 if m == mid], np.int64)
                if rows.size:
                    vf[rows] = jax.device_get(
                        self._value_m[mid](self.params[mid], obs[rows]))
            return vf

    def ping(self) -> str:
        """Health probe for FaultTolerantActorManager."""
        return "pong"

    # ---- weight sync (reference worker_set.py:365 sync_weights) -----
    def set_weights(self, weights) -> None:
        self.params = weights

    def get_weights(self):
        return self.params

    def set_explore_inputs(self, inputs: Dict[str, float]) -> None:
        """Update exploration scalars (e.g. {"epsilon": 0.1})."""
        self._explore_inputs = {
            k: np.asarray(v, np.float32) for k, v in inputs.items()}

    # ---- sampling ---------------------------------------------------
    def sample(self, num_timesteps: int) -> Dict[str, Any]:
        """Roll out ~num_timesteps across the vector env; returns a
        fragment batch of stacked columns [T, num_envs, ...] plus
        bootstrap values and completed-episode metrics."""
        from ray_tpu._private import spans as _spans
        with _spans.span("runner.sample", timesteps=num_timesteps):
            return self._sample_impl(num_timesteps)

    def _sample_impl(self, num_timesteps: int) -> Dict[str, Any]:
        import jax

        assert self.params is not None, "set_weights before sample"
        steps = max(1, num_timesteps // self.env.num_envs)
        cols: Dict[str, List[np.ndarray]] = {
            "obs": [], "actions": [], "rewards": [], "terminateds": [],
            "truncateds": [], "action_logp": [], "vf_preds": [],
            "raw_rewards": []}
        # sparse (t, env) -> true final observation at done steps, for
        # replay-based algorithms that bootstrap at update time
        finals_idx: List[Tuple[int, int]] = []
        finals_val: List[np.ndarray] = []
        for step_t in range(steps):
            with self._on_cpu():
                self._key, sub = jax.random.split(self._key)
            actions, logp, vf = self._forward_explore(self._obs, sub)
            env_actions = actions
            for ac in self._action_connectors:
                env_actions = ac(env_actions)
            obs_next, rewards, terms, truncs, _, final_obs = \
                self.env.step(env_actions)
            raw_rewards = rewards.copy()
            if self._env_pipeline is not None:
                obs_next, rewards, final_obs = self._env_pipeline.on_step(
                    obs_next, rewards, terms, truncs, final_obs)
            for i in np.nonzero(np.asarray(terms) | np.asarray(truncs))[0]:
                if final_obs[i] is not None:
                    finals_idx.append((step_t, int(i)))
                    finals_val.append(np.asarray(final_obs[i]))
            # Truncation is not termination: fold the bootstrap value of
            # the true final observation into the reward (exactly
            # equivalent to bootstrapping V there), so GAE can then treat
            # done = term|trunc uniformly as episode end.
            trunc_idx = np.nonzero(np.asarray(truncs)
                                   & ~np.asarray(terms))[0]
            if trunc_idx.size:
                f_obs = np.stack([final_obs[i] for i in trunc_idx])
                v_fin = self._forward_value(f_obs, lanes=trunc_idx)
                rewards = rewards.copy()
                rewards[trunc_idx] += self.gamma * v_fin
            cols["obs"].append(self._obs)
            cols["actions"].append(actions)
            cols["rewards"].append(rewards)
            cols["raw_rewards"].append(raw_rewards)
            cols["terminateds"].append(np.asarray(terms))
            cols["truncateds"].append(np.asarray(truncs))
            cols["action_logp"].append(logp)
            cols["vf_preds"].append(vf)

            self._ep_ret += rewards
            self._ep_len += 1
            done = np.asarray(terms) | np.asarray(truncs)
            for i in np.nonzero(done)[0]:
                self._completed.append({
                    "episode_return": float(self._ep_ret[i]),
                    "episode_len": int(self._ep_len[i]),
                    "lane": int(i)})
                self._ep_ret[i] = 0.0
                self._ep_len[i] = 0
            self._obs = obs_next

        batch = {k: np.stack(v) for k, v in cols.items()}  # [T, N, ...]
        # Fragment-end bootstrap: V(current obs). For envs whose last step
        # was done, this is the autoreset obs — GAE masks it with
        # (1 - done); truncation bootstrap was already folded into the
        # reward above.
        batch["bootstrap_value"] = self._forward_value(self._obs)
        # Obs after the final step: with obs[t+1], gives next_obs for
        # replay-based algorithms (done rows mask the autoreset obs).
        batch["last_obs"] = np.asarray(self._obs).copy()
        batch["final_obs_idx"] = (
            np.asarray(finals_idx, np.int64).reshape(-1, 2))
        batch["final_obs_vals"] = (
            np.stack(finals_val) if finals_val
            else np.zeros((0, *batch["last_obs"].shape[1:]),
                          batch["last_obs"].dtype))
        if self._ma:
            # lane -> module index (into module_order), for per-module
            # batch splitting on the learner side
            batch["lane_module"] = np.array(
                [self._module_order.index(m)
                 for m in self._lane_module_ids], np.int32)
            batch["module_order"] = list(self._module_order)
        metrics = self._completed
        self._completed = []
        batch["episode_metrics"] = metrics
        batch["worker_index"] = self.worker_index
        return batch

    # ---- replay-plane push path (APEX pattern) ----------------------
    def set_replay_writer(self, spec: Optional[Dict[str, Any]]) -> None:
        """Install (or clear, with None) the replay push client. The
        driver ships `spec` after spawning shards and again after every
        reshard: {"shards": [(shard_id, handle)], "max_inflight_per_shard",
        "gamma", "n_step"} — shard ActorHandles are picklable, so the
        spec travels as a plain actor-call argument."""
        if spec is None:
            self._replay_writer = None
            return
        from ray_tpu.rllib.utils.replay import ReplayWriter
        self._replay_writer = ReplayWriter(
            spec["shards"],
            max_inflight_per_shard=spec.get("max_inflight_per_shard", 4))
        self._replay_gamma = spec.get("gamma", self.gamma)
        self._replay_n_step = spec.get("n_step", 1)
        self._replay_seq = getattr(self, "_replay_seq", 0)

    def sample_to_replay(self, num_timesteps: int) -> Dict[str, Any]:
        """Roll out and push the transitions straight to the replay
        shards; only lightweight metadata returns to the driver (the
        fragment itself rides the scatter-put envelope to its shard,
        never back through the driver)."""
        writer = getattr(self, "_replay_writer", None)
        assert writer is not None, "set_replay_writer before sampling"
        # late import: dqn imports algorithm imports this module
        from ray_tpu.rllib.algorithms.dqn.dqn import fragment_to_transitions
        fragment = self.sample(num_timesteps)
        trans = fragment_to_transitions(
            fragment, self._replay_gamma, n_step=self._replay_n_step)
        self._replay_seq += 1
        shard = writer.push(
            trans, route_key=f"{self.worker_index}:{self._replay_seq}")
        return {
            "steps": int(len(trans["rewards"])),
            "episode_metrics": fragment.get("episode_metrics", []),
            "worker_index": self.worker_index,
            "pushed_to_shard": shard,
            "writer": writer.stats(),
        }

    def stop(self) -> None:
        self.env.close()
