"""Minimal gymnasium-compatible spaces.

The image has no gym/gymnasium; these cover what the RL stack needs
(reference envs expose gym.spaces.Box/Discrete — e.g.
rllib/env/single_agent_env_runner.py consumes env.observation_space /
action_space). API-compatible subset: sample(), contains(), shape/dtype/n.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


class Space:
    def __init__(self, shape: Tuple[int, ...], dtype):
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)

    def sample(self, rng: Optional[np.random.Generator] = None):
        raise NotImplementedError

    def contains(self, x) -> bool:
        raise NotImplementedError


class Box(Space):
    def __init__(self, low, high, shape: Optional[Tuple[int, ...]] = None,
                 dtype=np.float32):
        low = np.asarray(low, dtype=dtype)
        high = np.asarray(high, dtype=dtype)
        if shape is not None:
            low = np.broadcast_to(low, shape).astype(dtype)
            high = np.broadcast_to(high, shape).astype(dtype)
        super().__init__(low.shape, dtype)
        self.low, self.high = low, high

    def sample(self, rng: Optional[np.random.Generator] = None):
        rng = rng or np.random.default_rng()
        finite = np.isfinite(self.low) & np.isfinite(self.high)
        out = np.where(
            finite,
            rng.uniform(np.where(finite, self.low, 0.0),
                        np.where(finite, self.high, 1.0)),
            rng.standard_normal(self.shape))
        return out.astype(self.dtype)

    def contains(self, x) -> bool:
        x = np.asarray(x)
        return x.shape == self.shape and \
            bool(np.all(x >= self.low - 1e-6)) and \
            bool(np.all(x <= self.high + 1e-6))

    def __repr__(self):
        return f"Box({self.shape}, {self.dtype})"


class Discrete(Space):
    def __init__(self, n: int):
        super().__init__((), np.int64)
        self.n = int(n)

    def sample(self, rng: Optional[np.random.Generator] = None):
        rng = rng or np.random.default_rng()
        return int(rng.integers(self.n))

    def contains(self, x) -> bool:
        return 0 <= int(x) < self.n

    def __repr__(self):
        return f"Discrete({self.n})"
