"""CartPole-v1: numpy implementation of the classic control task.

Standard cart-pole dynamics (Barto, Sutton & Anderson 1983) with the
gymnasium CartPole-v1 constants: +1 reward per step, termination at
|x| > 2.4 or |theta| > 12deg, truncation at 500 steps. Built in because
the image ships no gym; used by the PPO/IMPALA learning tests
(BASELINE.json config 1; reference CI threshold
rllib/tuned_examples/impala/cartpole-impala.yaml:5-6).
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rllib.env.base import Env, register_env
from ray_tpu.rllib.env.spaces import Box, Discrete


class CartPoleEnv(Env):
    GRAVITY = 9.8
    MASSCART = 1.0
    MASSPOLE = 0.1
    LENGTH = 0.5          # half pole length
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * math.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        high = np.array([self.X_LIMIT * 2, np.inf,
                         self.THETA_LIMIT * 2, np.inf], np.float32)
        self.observation_space = Box(-high, high)
        self.action_space = Discrete(2)
        self._rng = np.random.default_rng()
        self._state = np.zeros(4, np.float32)
        self._steps = 0

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, 4).astype(np.float32)
        self._steps = 0
        return self._state.copy(), {}

    def step(self, action):
        x, x_dot, theta, theta_dot = self._state
        force = self.FORCE_MAG if int(action) == 1 else -self.FORCE_MAG
        costheta, sintheta = math.cos(theta), math.sin(theta)
        total_mass = self.MASSCART + self.MASSPOLE
        polemass_length = self.MASSPOLE * self.LENGTH

        temp = (force + polemass_length * theta_dot ** 2 * sintheta) \
            / total_mass
        theta_acc = (self.GRAVITY * sintheta - costheta * temp) / (
            self.LENGTH * (4.0 / 3.0
                           - self.MASSPOLE * costheta ** 2 / total_mass))
        x_acc = temp - polemass_length * theta_acc * costheta / total_mass

        x += self.TAU * x_dot
        x_dot += self.TAU * x_acc
        theta += self.TAU * theta_dot
        theta_dot += self.TAU * theta_acc
        self._state = np.array([x, x_dot, theta, theta_dot], np.float32)
        self._steps += 1

        terminated = bool(abs(x) > self.X_LIMIT
                          or abs(theta) > self.THETA_LIMIT)
        truncated = self._steps >= self.MAX_STEPS
        return self._state.copy(), 1.0, terminated, truncated, {}


register_env("CartPole-v1", CartPoleEnv)
