"""Synchronous vector env with autoreset.

reference parity: RLlib's EnvRunner steps gym.vector.VectorEnv
(env/single_agent_env_runner.py:34,139 — vectorized envs with autoreset
semantics: when a sub-env terminates/truncates, the returned obs is the
reset obs of the next episode).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.env.base import Env


class SyncVectorEnv:
    def __init__(self, env_fns: List[Callable[[], Env]]):
        self.envs = [fn() for fn in env_fns]
        self.num_envs = len(self.envs)
        self.observation_space = self.envs[0].observation_space
        self.action_space = self.envs[0].action_space

    def reset(self, seed: Optional[int] = None):
        obs, infos = [], []
        for i, e in enumerate(self.envs):
            o, info = e.reset(None if seed is None else seed + i)
            obs.append(o)
            infos.append(info)
        return np.stack(obs), infos

    def step(self, actions) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                     np.ndarray, List[Dict[str, Any]],
                                     np.ndarray]:
        """Returns (obs, rewards, terminated, truncated, infos,
        final_obs): when env i finishes, obs[i] is already the next
        episode's reset obs and final_obs[i] holds the true terminal
        observation (needed for correct value bootstrapping on
        truncation)."""
        # coerce once up front: a device-resident actions array handed
        # in here would otherwise pay one device->host sync per lane per
        # step inside the loop (each env coerces its scalar lane)
        actions = np.asarray(actions)
        obs, rewards, terms, truncs, infos = [], [], [], [], []
        final_obs = [None] * self.num_envs
        for i, (e, a) in enumerate(zip(self.envs, actions)):
            o, r, term, trunc, info = e.step(a)
            if term or trunc:
                final_obs[i] = o
                o, _ = e.reset()
            obs.append(o)
            rewards.append(r)
            terms.append(term)
            truncs.append(trunc)
            infos.append(info)
        return (np.stack(obs), np.asarray(rewards, np.float32),
                np.asarray(terms), np.asarray(truncs), infos, final_obs)

    def close(self) -> None:
        for e in self.envs:
            e.close()
