"""MiniPong: a procedurally generated Pong-class pixel environment.

Stands in for ALE Pong in the north-star Atari configs (BASELINE.md #2
/#3: PPO/IMPALA Pong with CPU EnvRunner fleets feeding a TPU learner;
reference tuned_examples/impala/pong-impala-fast.yaml:1-5) on images,
since the ALE is not installable in this environment. Raw frames are
168x168x3 RGB uint8 — the standard `wrap_atari` pipeline (MaxAndSkip ->
WarpFrame 84x84 grayscale -> FrameStack 4) produces exactly the Atari
tensor contract, exercising the full preprocessing path.

Game (single-player pong-squash): a ball launches from the top with a
random diagonal velocity and bounces off the top and side walls; the
agent moves a paddle along the bottom (LEFT/STAY/RIGHT). Returning the
ball scores +1 and re-launches it at a random angle; missing scores -1
and ends the episode; `max_returns` returns win the episode. Unlike
CatchPixels (straight drop, 7 steps), interception here requires
tracking diagonal motion through wall bounces over a ~20x longer
horizon — credit assignment and perception are Pong-shaped.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rllib.env.base import Env, register_env
from ray_tpu.rllib.env.spaces import Box, Discrete
from ray_tpu.rllib.env.wrappers import wrap_atari

SIZE = 21          # logical court cells per side
CELL = 8           # render pixels per cell -> 168x168
PADDLE_W = 3       # paddle width in cells (config "paddle_w" overrides)


class MiniPongRaw(Env):
    """Raw 168x168x3 uint8 frames, unwrapped.

    Config knobs scale difficulty for CI-budget learning smokes:
    paddle_w (wider paddle = denser reward), max_returns (episode
    length), speeds (horizontal velocity choices)."""

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        config = config or {}
        self.max_returns = int(config.get("max_returns", 5))
        self.paddle_w = int(config.get("paddle_w", PADDLE_W))
        self.speeds = tuple(config.get(
            "speeds", (-1.0, -0.5, 0.5, 1.0)))
        self.observation_space = Box(
            0, 255, (SIZE * CELL, SIZE * CELL, 3), np.uint8)
        self.action_space = Discrete(3)
        self._rng = np.random.default_rng(config.get("seed"))
        self._returns = 0
        self._bx = self._by = 0.0
        self._vx = self._vy = 0.0
        self._paddle = SIZE // 2

    def _launch(self) -> None:
        self._bx = float(self._rng.integers(3, SIZE - 3))
        self._by = 1.0
        self._vx = float(self._rng.choice(self.speeds))
        self._vy = 1.0

    def _render(self) -> np.ndarray:
        frame = np.zeros((SIZE * CELL, SIZE * CELL, 3), np.uint8)
        bx = int(np.clip(round(self._bx), 0, SIZE - 1))
        by = int(np.clip(round(self._by), 0, SIZE - 1))
        frame[by * CELL:(by + 1) * CELL,
              bx * CELL:(bx + 1) * CELL] = (236, 236, 236)
        pw = self.paddle_w
        lo = self._paddle - pw // 2
        lo = int(np.clip(lo, 0, SIZE - pw))
        frame[(SIZE - 1) * CELL:,
              lo * CELL:(lo + pw) * CELL] = (92, 186, 92)
        return frame

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._returns = 0
        self._paddle = SIZE // 2
        self._launch()
        return self._render(), {}

    def step(self, action: int):
        self._paddle = int(np.clip(self._paddle + (int(action) - 1),
                                   self.paddle_w // 2,
                                   SIZE - 1 - self.paddle_w // 2))
        self._bx += self._vx
        self._by += self._vy
        # side/top wall bounces
        if self._bx < 0:
            self._bx = -self._bx
            self._vx = -self._vx
        elif self._bx > SIZE - 1:
            self._bx = 2 * (SIZE - 1) - self._bx
            self._vx = -self._vx
        if self._by < 0:
            self._by = -self._by
            self._vy = 1.0
        reward = 0.0
        terminated = False
        if self._by >= SIZE - 1:  # reached the paddle row
            if abs(round(self._bx) - self._paddle) <= self.paddle_w // 2:
                reward = 1.0
                self._returns += 1
                if self._returns >= self.max_returns:
                    terminated = True
                else:
                    # bounce up with a fresh random horizontal direction
                    self._by = float(SIZE - 2)
                    self._vy = -1.0
                    self._vx = float(self._rng.choice(self.speeds))
            else:
                reward = -1.0
                terminated = True
        elif self._vy < 0 and self._by <= 1.0:
            # returning ball reaches the top: fall again
            self._vy = 1.0
        return self._render(), reward, terminated, False, {}


def make_minipong(config: Optional[Dict[str, Any]] = None) -> Env:
    """MiniPong with the DeepMind pipeline: [84, 84, 4] uint8 obs,
    4x frameskip, clipped rewards, 400-step (1600 raw frames) limit."""
    config = dict(config or {})
    frameskip = int(config.pop("frameskip", 2))
    return wrap_atari(
        MiniPongRaw(config), dim=84, framestack=4, frameskip=frameskip,
        clip_rewards=True, max_episode_steps=400)


register_env("MiniPong-v0", make_minipong)
# raw frames, no preprocessing: the connector-pipeline entry point
# (rllib/connectors deepmind_connectors supplies the DeepMind transforms)
register_env("MiniPongRaw-v0",
             lambda config=None: MiniPongRaw(config))
