"""CatchPixels: a deterministic image env with the Atari tensor contract.

reference parity: stands in for the ALE/atari_wrappers path
(rllib/env/wrappers/atari_wrappers.py — 84x84 grayscale, 4-frame stack,
uint8) on images without the ALE: same [84, 84, 4] uint8 observation
contract and Discrete actions, so conv catalogs, preprocessing and
throughput behave like the Pong north-star configs (BASELINE.md 2-3).

Game: a ball drops from the top in one of 7 columns; a 1-column paddle
at the bottom moves LEFT/STAY/RIGHT. Catch → +1, miss → -1. One drop per
episode (7 steps). Solvable to reward=1.0; random play ≈ -0.5.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rllib.env.base import Env, register_env
from ray_tpu.rllib.env.spaces import Box, Discrete

GRID = 7            # logical columns/rows
CELL = 12           # pixel block per logical cell → 84x84
FRAMES = 4


class CatchPixels(Env):
    def __init__(self, config: Optional[Dict[str, Any]] = None):
        config = config or {}
        self.observation_space = Box(0, 255, (84, 84, FRAMES), np.uint8)
        self.action_space = Discrete(3)
        self._rng = np.random.default_rng(config.get("seed"))
        self._frames = np.zeros((84, 84, FRAMES), np.uint8)
        self._ball_col = 0
        self._ball_row = 0
        self._paddle = GRID // 2

    def _render(self) -> np.ndarray:
        frame = np.zeros((84, 84), np.uint8)
        r, c = self._ball_row, self._ball_col
        if r < GRID:
            frame[r * CELL:(r + 1) * CELL, c * CELL:(c + 1) * CELL] = 255
        p = self._paddle
        frame[(GRID - 1) * CELL:, p * CELL:(p + 1) * CELL] = \
            np.maximum(frame[(GRID - 1) * CELL:, p * CELL:(p + 1) * CELL],
                       128)
        return frame

    def _obs(self) -> np.ndarray:
        self._frames = np.roll(self._frames, shift=-1, axis=-1)
        self._frames[..., -1] = self._render()
        return self._frames.copy()

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._ball_col = int(self._rng.integers(GRID))
        self._ball_row = 0
        self._paddle = GRID // 2
        self._frames[:] = 0
        return self._obs(), {}

    def step(self, action: int):
        self._paddle = int(np.clip(self._paddle + (int(action) - 1),
                                   0, GRID - 1))
        self._ball_row += 1
        terminated = self._ball_row >= GRID - 1
        reward = 0.0
        if terminated:
            reward = 1.0 if self._paddle == self._ball_col else -1.0
        return self._obs(), reward, terminated, False, {}


register_env("CatchPixels-v0", CatchPixels)
