"""Env API (gymnasium-style 5-tuple protocol) + registry.

reference parity: RLlib consumes gym.Env everywhere
(env/single_agent_env_runner.py:34 builds gym.vector envs; env registry
via tune.register_env). Same protocol here:
reset(seed) -> (obs, info); step(a) -> (obs, reward, terminated,
truncated, info). Register custom envs with register_env(name, creator);
gymnasium envs plug in unchanged if the package is present.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

_ENV_REGISTRY: Dict[str, Callable[[Dict[str, Any]], "Env"]] = {}


class Env:
    observation_space = None
    action_space = None

    def reset(self, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action):
        raise NotImplementedError

    def close(self) -> None:
        pass


def register_env(name: str,
                 creator: Callable[[Dict[str, Any]], Env]) -> None:
    """reference: ray.tune.register_env."""
    _ENV_REGISTRY[name] = creator


def make_env(name: str, config: Optional[Dict[str, Any]] = None) -> Env:
    config = config or {}
    if name in _ENV_REGISTRY:
        return _ENV_REGISTRY[name](config)
    # fall through to gymnasium when available
    try:
        import gymnasium
        return gymnasium.make(name)
    except ImportError:
        pass
    raise KeyError(
        f"unknown env {name!r}; register it with "
        "ray_tpu.rllib.register_env(name, creator)")
