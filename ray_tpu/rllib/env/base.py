"""Env API (gymnasium-style 5-tuple protocol) + registry.

reference parity: RLlib consumes gym.Env everywhere
(env/single_agent_env_runner.py:34 builds gym.vector envs; env registry
via tune.register_env). Same protocol here:
reset(seed) -> (obs, info); step(a) -> (obs, reward, terminated,
truncated, info). Register custom envs with register_env(name, creator);
gymnasium envs plug in unchanged if the package is present.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

_ENV_REGISTRY: Dict[str, Callable[[Dict[str, Any]], "Env"]] = {}


class Env:
    observation_space = None
    action_space = None

    def reset(self, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action):
        raise NotImplementedError

    def close(self) -> None:
        pass


def register_env(name: str,
                 creator: Callable[[Dict[str, Any]], Env]) -> None:
    """reference: ray.tune.register_env."""
    _ENV_REGISTRY[name] = creator


class GymnasiumAdapter(Env):
    """Wrap a gymnasium.Env into this protocol: keyword-only reset(seed=)
    becomes positional, and gymnasium spaces are converted to the local
    Box/Discrete so catalog isinstance dispatch works (reference RLlib
    consumes gym envs natively; this build's spaces are a subset)."""

    def __init__(self, gym_env):
        self._env = gym_env
        self.observation_space = self._convert(gym_env.observation_space)
        self.action_space = self._convert(gym_env.action_space)

    @staticmethod
    def _convert(space):
        import gymnasium
        from ray_tpu.rllib.env.spaces import Box, Discrete
        if isinstance(space, gymnasium.spaces.Discrete):
            return Discrete(int(space.n))
        if isinstance(space, gymnasium.spaces.Box):
            return Box(space.low, space.high, dtype=space.dtype)
        raise NotImplementedError(
            f"unsupported gymnasium space {type(space).__name__}")

    def reset(self, seed: Optional[int] = None):
        return self._env.reset(seed=seed)

    def step(self, action):
        import numpy as np
        a = np.asarray(action, self.action_space.dtype) \
            if self.action_space.shape else action
        return self._env.step(a)

    def close(self) -> None:
        self._env.close()


def make_env(name: str, config: Optional[Dict[str, Any]] = None) -> Env:
    config = config or {}
    if name in _ENV_REGISTRY:
        return _ENV_REGISTRY[name](config)
    # fall through to gymnasium when available
    try:
        import gymnasium
        return GymnasiumAdapter(gymnasium.make(name))
    except ImportError:
        pass
    raise KeyError(
        f"unknown env {name!r}; register it with "
        "ray_tpu.rllib.register_env(name, creator)")
