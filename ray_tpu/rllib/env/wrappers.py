"""Atari-style image env wrappers.

reference parity: rllib/env/wrappers/atari_wrappers.py — the standard
DeepMind preprocessing pipeline (NoopResetEnv, MaxAndSkipEnv, WarpFrame
84x84 grayscale, FrameStack, ClipRewardEnv) plus TimeLimit, composable
over this build's Env protocol (so they also apply to gymnasium/ALE envs
through GymnasiumAdapter when the ALE is installed). `wrap_atari` is the
reference's `wrap_deepmind` composition.

TPU-first notes: frames stay uint8 end to end (4x smaller trajectories
through the object store than f32); normalization happens inside the
jitted conv forward (core/catalog.py DiscreteConvModule). Resizing is
pure numpy — integer-factor area mean when exact, bilinear otherwise —
so env workers need no cv2 dependency.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_tpu.rllib.env.base import Env
from ray_tpu.rllib.env.spaces import Box


class Wrapper(Env):
    """Forward everything to the wrapped env by default."""

    def __init__(self, env: Env):
        self.env = env
        self.observation_space = env.observation_space
        self.action_space = env.action_space

    def reset(self, seed: Optional[int] = None):
        return self.env.reset(seed)

    def step(self, action):
        return self.env.step(action)

    def close(self) -> None:
        self.env.close()

    @property
    def unwrapped(self) -> Env:
        e = self.env
        while isinstance(e, Wrapper):
            e = e.env
        return e


def resize_image(img: np.ndarray, height: int, width: int) -> np.ndarray:
    """Resize [H, W] or [H, W, C] uint8/float arrays.

    Exact integer downscale -> area mean (what cv2 INTER_AREA does for
    integer factors); anything else -> bilinear, all vectorized numpy.
    """
    h, w = img.shape[:2]
    if h == height and w == width:
        return img
    if h % height == 0 and w % width == 0:
        fh, fw = h // height, w // width
        out = img.reshape(height, fh, width, fw, *img.shape[2:])
        return out.mean(axis=(1, 3)).astype(img.dtype)
    # bilinear sample grid
    ys = (np.arange(height) + 0.5) * h / height - 0.5
    xs = (np.arange(width) + 0.5) * w / width - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[:, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, :]
    if img.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    f = img.astype(np.float32)
    top = f[y0][:, x0] * (1 - wx) + f[y0][:, x1] * wx
    bot = f[y1][:, x0] * (1 - wx) + f[y1][:, x1] * wx
    return (top * (1 - wy) + bot * wy).astype(img.dtype)


def rgb_to_gray(obs: np.ndarray) -> np.ndarray:
    """ITU-R 601 luma [..., 3] -> [...] uint8, integer math (shared by
    WarpFrame and the connector pipeline so both stay bit-identical)."""
    return ((77 * obs[..., 0].astype(np.uint16)
             + 150 * obs[..., 1].astype(np.uint16)
             + 29 * obs[..., 2].astype(np.uint16)) >> 8).astype(np.uint8)


class WarpFrame(Wrapper):
    """Grayscale + resize to [dim, dim, 1] uint8 (reference WarpFrame:
    84x84 grayscale, the Nature-DQN observation)."""

    def __init__(self, env: Env, dim: int = 84):
        super().__init__(env)
        self.dim = dim
        self.observation_space = Box(0, 255, (dim, dim, 1), np.uint8)

    def _warp(self, obs: np.ndarray) -> np.ndarray:
        if obs.ndim == 3 and obs.shape[-1] == 3:
            obs = rgb_to_gray(obs)
        elif obs.ndim == 3 and obs.shape[-1] == 1:
            obs = obs[..., 0]
        out = resize_image(obs, self.dim, self.dim)
        return out[..., None]

    def reset(self, seed: Optional[int] = None):
        obs, info = self.env.reset(seed)
        return self._warp(np.asarray(obs)), info

    def step(self, action):
        obs, r, term, trunc, info = self.env.step(action)
        return self._warp(np.asarray(obs)), r, term, trunc, info


class FrameStack(Wrapper):
    """Stack the last k frames along the channel axis (reference
    FrameStack; [H, W, 1] x k -> [H, W, k])."""

    def __init__(self, env: Env, k: int = 4):
        super().__init__(env)
        self.k = k
        h, w, c = env.observation_space.shape
        self._frames = np.zeros((h, w, c * k),
                                env.observation_space.dtype)
        self.observation_space = Box(0, 255, (h, w, c * k),
                                     env.observation_space.dtype)
        self._c = c

    def _push(self, obs: np.ndarray) -> np.ndarray:
        self._frames = np.roll(self._frames, shift=-self._c, axis=-1)
        self._frames[..., -self._c:] = obs
        return self._frames.copy()

    def reset(self, seed: Optional[int] = None):
        obs, info = self.env.reset(seed)
        self._frames[:] = 0
        return self._push(np.asarray(obs)), info

    def step(self, action):
        obs, r, term, trunc, info = self.env.step(action)
        return self._push(np.asarray(obs)), r, term, trunc, info


class MaxAndSkipEnv(Wrapper):
    """Repeat the action `skip` times, return the elementwise max of the
    last two raw frames (reference MaxAndSkipEnv — defeats Atari sprite
    flicker and cuts inference cost 4x)."""

    def __init__(self, env: Env, skip: int = 4):
        super().__init__(env)
        self.skip = max(1, skip)

    def step(self, action):
        total = 0.0
        term = trunc = False
        info: Dict[str, Any] = {}
        prev = obs = None
        for _ in range(self.skip):
            prev = obs
            obs, r, term, trunc, info = self.env.step(action)
            total += r
            if term or trunc:
                break
        if prev is not None:
            obs = np.maximum(np.asarray(obs), np.asarray(prev))
        return obs, total, term, trunc, info


class ClipRewardEnv(Wrapper):
    """Clip rewards to {-1, 0, +1} by sign (reference ClipRewardEnv)."""

    def step(self, action):
        obs, r, term, trunc, info = self.env.step(action)
        return obs, float(np.sign(r)), term, trunc, info


class NoopResetEnv(Wrapper):
    """Take a random number of no-op actions on reset (reference
    NoopResetEnv — decorrelates initial states)."""

    def __init__(self, env: Env, noop_max: int = 30, noop_action: int = 0):
        super().__init__(env)
        self.noop_max = noop_max
        self.noop_action = noop_action
        self._rng = np.random.default_rng()

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        obs, info = self.env.reset(seed)
        for _ in range(int(self._rng.integers(0, self.noop_max + 1))):
            obs, _, term, trunc, info = self.env.step(self.noop_action)
            if term or trunc:
                obs, info = self.env.reset()
        return obs, info


class TimeLimit(Wrapper):
    """Truncate episodes at max_episode_steps (gym TimeLimit)."""

    def __init__(self, env: Env, max_episode_steps: int):
        super().__init__(env)
        self.max_episode_steps = max_episode_steps
        self._t = 0

    def reset(self, seed: Optional[int] = None):
        self._t = 0
        return self.env.reset(seed)

    def step(self, action):
        obs, r, term, trunc, info = self.env.step(action)
        self._t += 1
        if self._t >= self.max_episode_steps and not term:
            trunc = True
        return obs, r, term, trunc, info


def wrap_atari(env: Env, *, dim: int = 84, framestack: int = 4,
               frameskip: int = 4, clip_rewards: bool = True,
               noop_max: int = 0,
               max_episode_steps: Optional[int] = None) -> Env:
    """The reference's wrap_deepmind composition over this Env protocol:
    [NoopReset] -> MaxAndSkip -> WarpFrame -> [ClipReward] -> FrameStack
    [-> TimeLimit]. Output contract: [dim, dim, framestack] uint8."""
    if noop_max:
        env = NoopResetEnv(env, noop_max=noop_max)
    if frameskip > 1:
        env = MaxAndSkipEnv(env, skip=frameskip)
    env = WarpFrame(env, dim=dim)
    if clip_rewards:
        env = ClipRewardEnv(env)
    env = FrameStack(env, k=framestack)
    if max_episode_steps:
        env = TimeLimit(env, max_episode_steps)
    return env
