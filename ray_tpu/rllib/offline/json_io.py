"""Offline sample IO: JSONL fragment files.

reference parity: rllib/offline/json_writer.py (JsonWriter — sampled
batches to .json shards, rolling over at max_file_size) and
json_reader.py (JsonReader — reads shards, cycling forever for
training). Batches here are rollout *fragments* (the [T, N, ...] column
dicts EnvRunner.sample returns) so offline postprocessing (GAE for
MARWIL) can run exactly like the online path. Arrays encode as nested
lists with an explicit dtype tag; nesting carries the shape.
"""

from __future__ import annotations

import glob as _glob
import json
import os
from typing import Any, Dict, List, Optional

import numpy as np


def _encode(value: Any) -> Any:
    if isinstance(value, np.ndarray):
        return {"__nd__": value.tolist(), "dtype": str(value.dtype)}
    if isinstance(value, (np.integer, np.floating, np.bool_)):
        return value.item()
    return value


def _decode(value: Any) -> Any:
    if isinstance(value, dict) and "__nd__" in value:
        return np.asarray(value["__nd__"],
                          dtype=np.dtype(value["dtype"]))
    return value


class JsonWriter:
    """Append rollout fragments to JSONL shards under `path`."""

    def __init__(self, path: str,
                 max_file_size: int = 64 * 1024 * 1024):
        self.path = path
        self.max_file_size = max_file_size
        os.makedirs(path, exist_ok=True)
        self._shard = 0
        self._file = None

    def _current(self):
        if self._file is None or self._file.tell() > self.max_file_size:
            if self._file is not None:
                self._file.close()
                self._shard += 1
            name = os.path.join(self.path,
                                f"output-{self._shard:05d}.jsonl")
            self._file = open(name, "a", encoding="utf-8")
        return self._file

    def write(self, fragment: Dict[str, Any]) -> None:
        row = {k: _encode(v) for k, v in fragment.items()
               if k != "episode_metrics"}
        f = self._current()
        f.write(json.dumps(row) + "\n")
        f.flush()

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None


class JsonReader:
    """Cycle through JSONL shards, yielding decoded fragments."""

    def __init__(self, path: str, shuffle: bool = True,
                 seed: Optional[int] = None):
        if os.path.isdir(path):
            pattern = os.path.join(path, "*.jsonl")
        else:
            pattern = path
        self.files: List[str] = sorted(_glob.glob(pattern))
        if not self.files:
            raise FileNotFoundError(f"no offline data at {pattern!r}")
        # decode once up front: training cycles these fragments forever,
        # and the numpy arrays are smaller than the JSON text
        self._fragments: List[Dict[str, Any]] = []
        for fn in self.files:
            with open(fn, encoding="utf-8") as f:
                for line in f:
                    if line.strip():
                        row = json.loads(line)
                        self._fragments.append(
                            {k: _decode(v) for k, v in row.items()})
        if not self._fragments:
            raise ValueError(f"offline data at {pattern!r} is empty")
        self._order = np.arange(len(self._fragments))
        self._rng = np.random.default_rng(seed)
        self.shuffle = shuffle
        if shuffle:
            self._rng.shuffle(self._order)
        self._pos = 0

    def __len__(self) -> int:
        return len(self._fragments)

    def next(self) -> Dict[str, Any]:
        if self._pos >= len(self._order):
            self._pos = 0
            if self.shuffle:
                self._rng.shuffle(self._order)
        frag = self._fragments[self._order[self._pos]]
        self._pos += 1
        return dict(frag)
