"""Offline sample IO: JSONL fragment files.

reference parity: rllib/offline/json_writer.py (JsonWriter — sampled
batches to .json shards, rolling over at max_file_size) and
json_reader.py (JsonReader — reads shards, cycling forever for
training). Batches here are rollout *fragments* (the [T, N, ...] column
dicts EnvRunner.sample returns) so offline postprocessing (GAE for
MARWIL) can run exactly like the online path. Arrays encode as nested
lists with an explicit dtype tag; nesting carries the shape.
"""

from __future__ import annotations

import glob as _glob
import json
import os
from typing import Any, Dict, List, Optional

import numpy as np


def _encode(value: Any) -> Any:
    if isinstance(value, np.ndarray):
        return {"__nd__": value.tolist(), "dtype": str(value.dtype)}
    if isinstance(value, (np.integer, np.floating, np.bool_)):
        return value.item()
    return value


def _decode(value: Any) -> Any:
    if isinstance(value, dict) and "__nd__" in value:
        return np.asarray(value["__nd__"],
                          dtype=np.dtype(value["dtype"]))
    return value


class JsonWriter:
    """Append rollout fragments to JSONL shards under `path`."""

    def __init__(self, path: str,
                 max_file_size: int = 64 * 1024 * 1024):
        self.path = path
        self.max_file_size = max_file_size
        os.makedirs(path, exist_ok=True)
        self._shard = 0
        self._file = None

    def _current(self):
        if self._file is None or self._file.tell() > self.max_file_size:
            if self._file is not None:
                self._file.close()
                self._shard += 1
            name = os.path.join(self.path,
                                f"output-{self._shard:05d}.jsonl")
            self._file = open(name, "a", encoding="utf-8")
        return self._file

    def write(self, fragment: Dict[str, Any]) -> None:
        row = {k: _encode(v) for k, v in fragment.items()
               if k != "episode_metrics"}
        f = self._current()
        f.write(json.dumps(row) + "\n")
        f.flush()

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None


class JsonReader:
    """Cycle through JSONL shards, yielding decoded fragments.

    Shards are decoded lazily with at most `max_cached_shards` decoded
    shards held in memory (the reference JsonReader likewise streams
    shards instead of materializing the whole dataset). With `shuffle`
    on, fragments are drawn from a WORKING SET of up to
    `max_cached_shards` concurrently-open shards — each draw picks a
    shard weighted by its remaining fragments, then a random fragment
    within it; exhausted shards are replaced from the (reshuffled per
    epoch) shard order. This mixes consecutive samples across shards at
    bounded memory, so shard-correlated datasets (one shard per worker/
    policy) don't feed long single-shard runs to the learner.
    """

    def __init__(self, path: str, shuffle: bool = True,
                 seed: Optional[int] = None,
                 max_cached_shards: int = 2):
        if os.path.isdir(path):
            pattern = os.path.join(path, "*.jsonl")
        else:
            pattern = path
        self.files: List[str] = sorted(_glob.glob(pattern))
        if not self.files:
            raise FileNotFoundError(f"no offline data at {pattern!r}")
        # count fragments per shard without decoding (cheap line scan)
        self._counts: List[int] = []
        for fn in self.files:
            n = 0
            with open(fn, encoding="utf-8") as f:
                for line in f:
                    if line.strip():
                        n += 1
            self._counts.append(n)
        # drop empty shards so the cycle loop never stalls on one
        keep = [i for i, n in enumerate(self._counts) if n > 0]
        self.files = [self.files[i] for i in keep]
        self._counts = [self._counts[i] for i in keep]
        if not self.files:
            raise ValueError(f"offline data at {pattern!r} is empty")
        self.max_cached_shards = max(1, int(max_cached_shards))
        self._rng = np.random.default_rng(seed)
        self.shuffle = shuffle
        self._shard_order: List[int] = list(range(len(self.files)))
        if shuffle:
            self._rng.shuffle(self._shard_order)
        self._next_shard = 0
        # working set: shard_ix -> (decoded fragments, remaining order)
        self._open: Dict[int, Any] = {}

    def __len__(self) -> int:
        return int(sum(self._counts))

    def _load_shard(self, ix: int) -> List[Dict[str, Any]]:
        frags: List[Dict[str, Any]] = []
        with open(self.files[ix], encoding="utf-8") as f:
            for line in f:
                if line.strip():
                    row = json.loads(line)
                    frags.append(
                        {k: _decode(v) for k, v in row.items()})
        return frags

    def _refill(self) -> None:
        while len(self._open) < min(self.max_cached_shards,
                                    len(self.files)):
            if self._next_shard >= len(self._shard_order):
                self._next_shard = 0
                if self.shuffle:
                    self._rng.shuffle(self._shard_order)
            ix = self._shard_order[self._next_shard]
            self._next_shard += 1
            if ix in self._open:
                # tiny datasets: every shard already open
                break
            order = list(range(self._counts[ix]))
            if self.shuffle:
                self._rng.shuffle(order)
            else:
                order.reverse()  # pop() from the end -> forward order
            self._open[ix] = (self._load_shard(ix), order)

    def next(self) -> Dict[str, Any]:
        self._refill()
        if self.shuffle:
            # weight by remaining fragments so every fragment in the
            # working set is equally likely
            keys = list(self._open)
            weights = np.asarray(
                [len(self._open[k][1]) for k in keys], np.float64)
            ix = keys[int(self._rng.choice(
                len(keys), p=weights / weights.sum()))]
        else:
            ix = next(iter(self._open))
        frags, order = self._open[ix]
        frag = frags[order.pop()]
        if not order:
            del self._open[ix]
        return dict(frag)
