"""Connector pipelines: composable obs/reward/action transforms.

reference parity: rllib/connectors/connector.py:1 (Connector /
ConnectorPipeline), connectors/agent/obs_preproc.py (obs preprocessing),
agent/mean_std_filter.py, agent/clip_reward.py, action connectors —
preprocessing decoupled from env wrappers so the same env can feed
different algorithms with different pipelines, and pipeline state
(frame stacks, running filters) checkpoints with the runner.

TPU-first shape: connectors transform the VECTORIZED lane batch at the
EnvRunner boundary — obs [N, ...] / rewards [N] across all vector lanes
in one numpy op — instead of the reference's per-agent python dicts, so
per-step python cost is O(1) in lane count.

The step contract carries the per-lane true FINAL observations of
episodes that ended this step (None for live lanes): bootstrap values
are computed from them, so they must pass through the same obs
transforms as the stream itself.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.env.spaces import Box


class EnvConnector:
    """Observation/reward-side connector (reference agent connectors)."""

    def observation_space(self, space):
        return space

    def on_reset(self, obs: np.ndarray) -> np.ndarray:
        return obs

    def on_step(self, obs: np.ndarray, rewards: np.ndarray,
                terms: np.ndarray, truncs: np.ndarray,
                finals: List[Optional[np.ndarray]]):
        """-> (obs, rewards, finals), each transformed."""
        return obs, rewards, finals

    # pipeline state rides runner checkpoints (reference Connector
    # serialization)
    def get_state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass


class ActionConnector:
    """Action-side connector (reference action connectors): transforms
    the batched actions [N, ...] on their way to the env."""

    def __call__(self, actions: np.ndarray) -> np.ndarray:
        return actions


class ConnectorPipeline:
    """Ordered composition (reference ConnectorPipeline)."""

    def __init__(self, connectors: Optional[List[EnvConnector]] = None):
        self.connectors = list(connectors or [])

    def observation_space(self, space):
        for c in self.connectors:
            space = c.observation_space(space)
        return space

    def on_reset(self, obs):
        for c in self.connectors:
            obs = c.on_reset(obs)
        return obs

    def on_step(self, obs, rewards, terms, truncs, finals):
        for c in self.connectors:
            obs, rewards, finals = c.on_step(obs, rewards, terms,
                                             truncs, finals)
        return obs, rewards, finals

    def get_state(self) -> List[Dict[str, Any]]:
        return [c.get_state() for c in self.connectors]

    def set_state(self, states: List[Dict[str, Any]]) -> None:
        for c, s in zip(self.connectors, states):
            c.set_state(s)


class GrayscaleResizeConnector(EnvConnector):
    """RGB [N, H, W, 3] -> resized grayscale [N, dim, dim, 1] uint8
    (reference agent/obs_preproc.py / WarpFrame as a connector)."""

    def __init__(self, dim: int = 84):
        self.dim = dim

    def observation_space(self, space):
        return Box(0, 255, (self.dim, self.dim, 1), np.uint8)

    def _warp_one(self, obs: np.ndarray) -> np.ndarray:
        from ray_tpu.rllib.env.wrappers import resize_image, rgb_to_gray
        if obs.shape[-1] == 3:
            gray = rgb_to_gray(obs)  # same luma as WarpFrame:
        else:                        # pipelines stay bit-identical
            gray = obs[..., 0]
        return resize_image(gray, self.dim, self.dim
                            ).astype(np.uint8)[..., None]

    def _warp(self, obs: np.ndarray) -> np.ndarray:
        return np.stack([self._warp_one(o) for o in obs])

    def on_reset(self, obs):
        return self._warp(obs)

    def on_step(self, obs, rewards, terms, truncs, finals):
        finals = [None if f is None else self._warp_one(np.asarray(f))
                  for f in finals]
        return self._warp(obs), rewards, finals


class FrameStackConnector(EnvConnector):
    """Stack the last k frames per lane along the channel axis
    (reference FrameStack as a stateful agent connector). Reset/episode
    boundaries zero the lane's history + push the first frame — the
    exact env/wrappers.FrameStack semantics, bit-identical pipelines."""

    def __init__(self, k: int = 4):
        self.k = k
        self._stack: Optional[np.ndarray] = None  # [N, H, W, C*k]

    def observation_space(self, space):
        h, w, c = space.shape
        return Box(0, 255, (h, w, c * self.k), space.dtype)

    def on_reset(self, obs):
        n, h, w, c = obs.shape
        self._stack = np.zeros((n, h, w, c * self.k), obs.dtype)
        self._stack[..., -c:] = obs
        return self._stack.copy()

    def on_step(self, obs, rewards, terms, truncs, finals):
        c = obs.shape[-1]
        # finals first: an episode's true final stack is the PRE-update
        # lane history rolled with the final frame
        out_finals: List[Optional[np.ndarray]] = []
        for lane, f in enumerate(finals):
            if f is None:
                out_finals.append(None)
            else:
                out_finals.append(np.concatenate(
                    [self._stack[lane][..., c:], np.asarray(f)],
                    axis=-1))
        self._stack = np.concatenate(
            [self._stack[..., c:], obs], axis=-1)
        done = np.asarray(terms) | np.asarray(truncs)
        if done.any():
            # episode boundary: the incoming obs is the autoreset frame;
            # zero the lane's history like a wrapper-stack reset would
            lanes = np.nonzero(done)[0]
            self._stack[lanes] = 0
            self._stack[lanes, ..., -c:] = obs[lanes]
        return self._stack.copy(), rewards, out_finals

    def get_state(self):
        return {"stack": None if self._stack is None
                else self._stack.copy()}

    def set_state(self, state):
        self._stack = state.get("stack")


class ClipRewardConnector(EnvConnector):
    """sign() or [-bound, bound] clip (reference agent/clip_reward.py)."""

    def __init__(self, sign: bool = True, bound: float = 1.0):
        self.sign = sign
        self.bound = bound

    def on_step(self, obs, rewards, terms, truncs, finals):
        if self.sign:
            return obs, np.sign(rewards).astype(np.float32), finals
        return obs, np.clip(rewards, -self.bound,
                            self.bound).astype(np.float32), finals


class MeanStdFilterConnector(EnvConnector):
    """Running mean/std observation normalization (reference
    agent/mean_std_filter.py — Welford accumulation; the filter state
    checkpoints with the runner). Final observations are normalized with
    the current filter but do not update it."""

    def __init__(self, clip: float = 10.0, eps: float = 1e-8):
        self.clip = clip
        self.eps = eps
        self._count = 0.0
        self._mean: Optional[np.ndarray] = None
        self._m2: Optional[np.ndarray] = None

    def _update(self, obs: np.ndarray) -> None:
        # batched (Chan et al.) Welford merge: one vectorized update per
        # step regardless of lane count — O(1) python in N
        batch = np.asarray(obs, np.float64)
        n = batch.shape[0]
        if n == 0:
            return
        bmean = batch.mean(axis=0)
        bm2 = ((batch - bmean) ** 2).sum(axis=0)
        if self._mean is None:
            self._mean = bmean
            self._m2 = bm2
            self._count = float(n)
            return
        delta = bmean - self._mean
        total = self._count + n
        self._mean = self._mean + delta * (n / total)
        self._m2 = self._m2 + bm2 + delta ** 2 * (self._count * n / total)
        self._count = total

    def _apply(self, obs: np.ndarray) -> np.ndarray:
        if self._mean is None or self._count < 2:
            return np.asarray(obs, np.float32)
        std = np.sqrt(self._m2 / (self._count - 1)) + self.eps
        out = (np.asarray(obs, np.float64) - self._mean) / std
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    def observation_space(self, space):
        return Box(-self.clip, self.clip, space.shape, np.float32)

    def on_reset(self, obs):
        self._update(obs)
        return self._apply(obs)

    def on_step(self, obs, rewards, terms, truncs, finals):
        self._update(obs)
        finals = [None if f is None else self._apply(f) for f in finals]
        return self._apply(obs), rewards, finals

    def get_state(self):
        # copies: checkpoint state must not alias the live Welford
        # accumulators (updated in place every step)
        return {"count": self._count,
                "mean": None if self._mean is None else self._mean.copy(),
                "m2": None if self._m2 is None else self._m2.copy()}

    def set_state(self, state):
        self._count = state.get("count", 0.0)
        mean = state.get("mean")
        m2 = state.get("m2")
        self._mean = None if mean is None else np.array(mean)
        self._m2 = None if m2 is None else np.array(m2)


class ClipActionConnector(ActionConnector):
    """Clip continuous actions into the env's bounds (reference action
    connectors' clip)."""

    def __init__(self, low, high):
        self.low = np.asarray(low)
        self.high = np.asarray(high)

    def __call__(self, actions):
        return np.clip(actions, self.low, self.high)


def deepmind_connectors(dim: int = 84, framestack: int = 4,
                        clip_rewards: bool = True
                        ) -> List[EnvConnector]:
    """The DeepMind Atari preprocessing as a connector pipeline
    (reference wrap_deepmind ported onto connectors; frame-skip stays an
    env wrapper because it changes stepping, not observations)."""
    out: List[EnvConnector] = [GrayscaleResizeConnector(dim=dim),
                               FrameStackConnector(k=framestack)]
    if clip_rewards:
        out.append(ClipRewardConnector(sign=True))
    return out
