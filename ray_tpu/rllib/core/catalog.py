"""Catalog: default network builders per observation/action space.

reference parity: rllib/core/models/catalog.py:33 (Catalog builds
encoders/heads per space) and the legacy ModelCatalog
(rllib/models/catalog.py:205). Default here: shared MLP torso with policy
+ value heads — the standard PPO/IMPALA CartPole/control net.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

from ray_tpu.rllib.core.rl_module import Categorical, RLModule
from ray_tpu.rllib.env.spaces import Box, Discrete


def _mlp_init(key, sizes, scale_last: float = 0.01):
    import jax
    import jax.numpy as jnp

    params = []
    keys = jax.random.split(key, len(sizes) - 1)
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        w_scale = (2.0 / fan_in) ** 0.5
        if i == len(sizes) - 2 and scale_last is not None:
            w_scale = scale_last
        params.append({
            "w": (jax.random.normal(keys[i], (fan_in, fan_out), jnp.float32)
                  * w_scale),
            "b": jnp.zeros((fan_out,), jnp.float32),
        })
    return params


def _mlp_apply(params, x):
    import jax
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jax.nn.tanh(x)
    return x


class DiscreteMLPModule(RLModule):
    """Actor-critic MLP for Discrete action spaces."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hiddens: Sequence[int] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hiddens = tuple(hiddens)

    def init_params(self, key) -> Dict[str, Any]:
        import jax
        k1, k2, k3 = jax.random.split(key, 3)
        torso = [self.obs_dim, *self.hiddens]
        return {
            "torso": _mlp_init(k1, torso, scale_last=None),
            "pi": _mlp_init(k2, [self.hiddens[-1], self.num_actions]),
            "vf": _mlp_init(k3, [self.hiddens[-1], 1], scale_last=1.0),
        }

    def forward_train(self, params, batch):
        import jax
        h = _mlp_apply(params["torso"], batch["obs"])
        h = jax.nn.tanh(h)
        logits = _mlp_apply(params["pi"], h)
        vf = _mlp_apply(params["vf"], h)[..., 0]
        return {"action_dist_inputs": logits, "vf_preds": vf}

    def action_dist(self, dist_inputs) -> Categorical:
        return Categorical(dist_inputs)


def default_module_for(observation_space, action_space,
                       hiddens: Sequence[int] = (64, 64)) -> RLModule:
    """reference Catalog._get_encoder_config dispatch, reduced to the
    spaces this build ships."""
    if isinstance(action_space, Discrete) and \
            isinstance(observation_space, Box) and \
            len(observation_space.shape) == 1:
        return DiscreteMLPModule(
            observation_space.shape[0], action_space.n, hiddens)
    raise NotImplementedError(
        f"no default module for obs={observation_space} "
        f"act={action_space}; pass a custom RLModule via config.rl_module()")
