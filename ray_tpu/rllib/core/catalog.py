"""Catalog: default network builders per observation/action space.

reference parity: rllib/core/models/catalog.py:33 (Catalog builds
encoders/heads per space) and the legacy ModelCatalog
(rllib/models/catalog.py:205). Default here: shared MLP torso with policy
+ value heads — the standard PPO/IMPALA CartPole/control net.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

from ray_tpu.rllib.core.rl_module import Categorical, RLModule
from ray_tpu.rllib.env.spaces import Box, Discrete


def _mlp_init(key, sizes, scale_last: float = 0.01):
    import jax
    import jax.numpy as jnp

    params = []
    keys = jax.random.split(key, len(sizes) - 1)
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        w_scale = (2.0 / fan_in) ** 0.5
        if i == len(sizes) - 2 and scale_last is not None:
            w_scale = scale_last
        params.append({
            "w": (jax.random.normal(keys[i], (fan_in, fan_out), jnp.float32)
                  * w_scale),
            "b": jnp.zeros((fan_out,), jnp.float32),
        })
    return params


def _mlp_apply(params, x):
    import jax
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jax.nn.tanh(x)
    return x


class DiscreteMLPModule(RLModule):
    """Actor-critic MLP for Discrete action spaces."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hiddens: Sequence[int] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hiddens = tuple(hiddens)

    def init_params(self, key) -> Dict[str, Any]:
        import jax
        k1, k2, k3 = jax.random.split(key, 3)
        torso = [self.obs_dim, *self.hiddens]
        return {
            "torso": _mlp_init(k1, torso, scale_last=None),
            "pi": _mlp_init(k2, [self.hiddens[-1], self.num_actions]),
            "vf": _mlp_init(k3, [self.hiddens[-1], 1], scale_last=1.0),
        }

    def forward_train(self, params, batch):
        import jax
        h = _mlp_apply(params["torso"], batch["obs"])
        h = jax.nn.tanh(h)
        logits = _mlp_apply(params["pi"], h)
        vf = _mlp_apply(params["vf"], h)[..., 0]
        return {"action_dist_inputs": logits, "vf_preds": vf}

    def action_dist(self, dist_inputs) -> Categorical:
        return Categorical(dist_inputs)


class DiscreteConvModule(RLModule):
    """Actor-critic conv net for image observations ([H, W, C] uint8).

    The classic Atari torso (reference models/catalog.py CNN defaults /
    the Nature-DQN stack used by the Pong tuned examples): conv 32@8s4,
    64@4s2, 64@3s1 → dense 512 → policy/value heads. Convs map onto the
    MXU; uint8 pixels are normalized to [0,1] inside the jitted forward
    so frames cross the object store as compact uint8.
    """

    CONVS = ((32, 8, 4), (64, 4, 2), (64, 3, 1))  # (out_ch, kernel, stride)

    def __init__(self, obs_shape: Sequence[int], num_actions: int,
                 dense: int = 512):
        assert len(obs_shape) == 3, f"need [H,W,C] obs, got {obs_shape}"
        self.obs_shape = tuple(obs_shape)
        self.num_actions = num_actions
        self.dense = dense

    def _conv_out_size(self) -> int:
        h, w, _ = self.obs_shape
        for _, k, s in self.CONVS:
            h = (h - k) // s + 1
            w = (w - k) // s + 1
        return h * w * self.CONVS[-1][0]

    def init_params(self, key) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        keys = jax.random.split(key, len(self.CONVS) + 3)
        params: Dict[str, Any] = {"convs": []}
        in_ch = self.obs_shape[-1]
        for i, (out_ch, k, _s) in enumerate(self.CONVS):
            fan_in = k * k * in_ch
            params["convs"].append({
                "w": (jax.random.normal(keys[i], (k, k, in_ch, out_ch),
                                        jnp.float32)
                      * (2.0 / fan_in) ** 0.5),
                "b": jnp.zeros((out_ch,), jnp.float32),
            })
            in_ch = out_ch
        flat = self._conv_out_size()
        params["dense"] = _mlp_init(keys[-3], [flat, self.dense],
                                    scale_last=None)
        params["pi"] = _mlp_init(keys[-2], [self.dense, self.num_actions])
        params["vf"] = _mlp_init(keys[-1], [self.dense, 1], scale_last=1.0)
        return params

    def forward_train(self, params, batch):
        import jax
        import jax.numpy as jnp
        from jax import lax

        x = batch["obs"].astype(jnp.float32) / 255.0
        for layer, (_out, _k, s) in zip(params["convs"], self.CONVS):
            x = lax.conv_general_dilated(
                x, layer["w"], window_strides=(s, s), padding="VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC")) + layer["b"]
            x = jax.nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        h = jax.nn.relu(_mlp_apply(params["dense"], x))
        logits = _mlp_apply(params["pi"], h)
        vf = _mlp_apply(params["vf"], h)[..., 0]
        return {"action_dist_inputs": logits, "vf_preds": vf}

    def action_dist(self, dist_inputs) -> Categorical:
        return Categorical(dist_inputs)


def default_module_for(observation_space, action_space,
                       hiddens: Sequence[int] = (64, 64)) -> RLModule:
    """reference Catalog._get_encoder_config dispatch, reduced to the
    spaces this build ships."""
    if isinstance(action_space, Discrete) and \
            isinstance(observation_space, Box):
        if len(observation_space.shape) == 1:
            return DiscreteMLPModule(
                observation_space.shape[0], action_space.n, hiddens)
        if len(observation_space.shape) == 3:
            return DiscreteConvModule(
                observation_space.shape, action_space.n)
    raise NotImplementedError(
        f"no default module for obs={observation_space} "
        f"act={action_space}; pass a custom RLModule via config.rl_module()")
