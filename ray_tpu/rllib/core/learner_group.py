"""LearnerGroup: one local learner or a mesh-coupled gang of learner actors.

reference parity: rllib/core/learner/learner_group.py:63 — local mode
(num_learners=0, learner in-process: the CartPole north-star config) or
remote mode where learner actors form a jax.distributed process group
exactly as the reference LearnerGroup reuses Train's BackendExecutor to
build a torch process group (learner_group.py:103-115). Gradients sync
through XLA collectives over the shared 'data' mesh (the DDP-allreduce
equivalent of torch_learner.py:378-390) — every learner holds identical
replicated params after every step, so there is no unsound weight
averaging and Adam semantics match single-learner training exactly.
On TPU pods each learner process contributes its chips and the psum
rides ICI; in chip-free CI the same code runs over multi-process CPU.
"""

from __future__ import annotations

import uuid
from typing import Any, Callable, Dict, List, Optional

import numpy as np


class _MeshLearnerActor:
    """One rank of the learner gang; must run in a fresh worker process
    (jax.distributed can only initialize before any other jax use, which
    the gang's unique runtime-env pool key guarantees)."""

    def __init__(self, factory: Callable[[], Any], coordinator: str,
                 world: int, rank: int, seed: int):
        import os

        import jax
        # Honor an explicit platform pin (the chip-free test ladder sets
        # JAX_PLATFORMS=cpu): device plugins can re-assert themselves over
        # the env var, so pin through jax.config like tests/conftest.py.
        plat = os.environ.get("JAX_PLATFORMS")
        if plat:
            jax.config.update("jax_platforms", plat)
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=world, process_id=rank)
        self.rank = rank
        self.world = world
        self.learner = factory()
        self.learner.build_distributed(seed=seed)

    def ping(self) -> str:
        return "pong"

    def _local_shard(self, batch: Dict[str, np.ndarray]
                     ) -> Dict[str, np.ndarray]:
        """Equal per-rank slices along each column's data axis (truncating
        the remainder so every rank runs identical jit step counts)."""
        first = next(iter(batch))
        axis = self.learner.data_axis_for(first)
        n = batch[first].shape[axis]
        per = n // self.world
        out = {}
        for k, v in batch.items():
            a = self.learner.data_axis_for(k)
            sl = [slice(None)] * v.ndim
            sl[a] = slice(self.rank * per, (self.rank + 1) * per)
            out[k] = v[tuple(sl)]
        return out

    def update(self, batch, minibatch_size, num_iters, seed):
        return self.learner.update_distributed(
            self._local_shard(batch), minibatch_size, num_iters, seed)

    def additional_update(self, **kw):
        return self.learner.additional_update(**kw)

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, w):
        self.learner.set_weights(w)

    def get_state(self):
        return self.learner.get_state()

    def set_state(self, s):
        self.learner.set_state(s)


def _free_port() -> int:
    from ray_tpu._private.rpc import find_free_port
    return find_free_port()


class LearnerGroup:
    def __init__(self, learner_factory: Callable[[], Any],
                 num_learners: int = 0, seed: int = 0):
        self._num_learners = num_learners
        if num_learners == 0:
            self._local = learner_factory()
            self._local.build(seed=seed)
            self._actors: List[Any] = []
            return
        import ray_tpu

        self._local = None
        # Fresh worker processes for the gang: the unique runtime-env key
        # gives them their own worker-pool bucket, so jax.distributed
        # initializes before any other jax use in those processes.
        # One host (CPU) device per gang process: the virtual-device test
        # flag (--xla_force_host_platform_device_count=8) would otherwise
        # leak in and force per-process shard sizes to be divisible by 8.
        # Preserve any other XLA_FLAGS the operator set (TPU tuning flags
        # etc.) — only the host-device-count flag is replaced.
        import os
        import re
        flags = re.sub(r"--xla_force_host_platform_device_count=\S+", "",
                       os.environ.get("XLA_FLAGS", "")).strip()
        gang_env = {"env_vars": {
            "RAY_TPU_LEARNER_GANG": uuid.uuid4().hex,
            "XLA_FLAGS": (flags + " "
                          "--xla_force_host_platform_device_count=1"
                          ).strip(),
        }}
        coordinator = f"127.0.0.1:{_free_port()}"
        actor_cls = ray_tpu.remote(_MeshLearnerActor)
        self._actors = [
            actor_cls.options(num_cpus=1, runtime_env=gang_env).remote(
                learner_factory, coordinator, num_learners, rank, seed)
            for rank in range(num_learners)
        ]
        # Barrier on gang readiness (rank 0 hosts the coordinator; all
        # ranks block in jax.distributed.initialize until every peer is
        # up — mirror of the reference's process-group rendezvous).
        ray_tpu.get([a.ping.remote() for a in self._actors], timeout=300)

    def __len__(self) -> int:
        return max(1, self._num_learners)

    # ---- updates ----------------------------------------------------
    def update(self, batch: Dict[str, np.ndarray],
               minibatch_size: Optional[int] = None,
               num_iters: int = 1, seed: int = 0) -> Dict[str, float]:
        if self._local is not None:
            return self._local.update(batch, minibatch_size, num_iters,
                                      seed)
        import ray_tpu
        # Same full batch + same seed to every rank: each slices its own
        # equal shard and all ranks enter the jitted collective step the
        # same number of times.
        stats = ray_tpu.get([
            a.update.remote(batch, minibatch_size, num_iters, seed)
            for a in self._actors
        ], timeout=600)
        # Scalars mean-reduce across ranks; array stats (per-sample TD
        # errors + their batch indexes) concatenate in rank order — each
        # rank reported its own shard of the global batch.
        out: Dict[str, Any] = {}
        for k in stats[0]:
            if getattr(stats[0][k], "ndim", 0):
                out[k] = np.concatenate([np.asarray(s[k]) for s in stats])
            else:
                out[k] = float(np.mean([s[k] for s in stats]))
        return out

    def additional_update(self, **kwargs) -> Dict[str, Any]:
        if self._local is not None:
            return self._local.additional_update(**kwargs)
        import ray_tpu
        outs = ray_tpu.get(
            [a.additional_update.remote(**kwargs) for a in self._actors],
            timeout=120)
        return outs[0]

    # ---- weights ----------------------------------------------------
    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        import ray_tpu
        return ray_tpu.get(self._actors[0].get_weights.remote(),
                           timeout=600)

    def set_weights(self, w) -> None:
        if self._local is not None:
            self._local.set_weights(w)
            return
        import ray_tpu
        ray_tpu.get([a.set_weights.remote(w) for a in self._actors],
                    timeout=600)

    def get_state(self):
        if self._local is not None:
            return self._local.get_state()
        import ray_tpu
        return ray_tpu.get(self._actors[0].get_state.remote(), timeout=600)

    def set_state(self, state) -> None:
        if self._local is not None:
            self._local.set_state(state)
            return
        import ray_tpu
        ray_tpu.get([a.set_state.remote(state) for a in self._actors],
                    timeout=600)

    def shutdown(self) -> None:
        import ray_tpu
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001 - actor already dead
                pass
        self._actors = []
