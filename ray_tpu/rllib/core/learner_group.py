"""LearnerGroup: one local learner or a mesh-coupled gang of learner actors.

reference parity: rllib/core/learner/learner_group.py:63 — local mode
(num_learners=0, learner in-process: the CartPole north-star config) or
remote mode where learner actors form a jax.distributed process group
exactly as the reference LearnerGroup reuses Train's BackendExecutor to
build a torch process group (learner_group.py:103-115). Gradients sync
through XLA collectives over the shared 'data' mesh (the DDP-allreduce
equivalent of torch_learner.py:378-390) — every learner holds identical
replicated params after every step, so there is no unsound weight
averaging and Adam semantics match single-learner training exactly.
On TPU pods each learner process contributes its chips and the psum
rides ICI; in chip-free CI the same code runs over multi-process CPU.

Elastic mode (elastic_min_learners set): the gang survives member
death and explicit resizes. The driver keeps a host-side state cache
(params/opt state, refreshed every `state_refresh_every` successful
updates, default 1 — the gang's durable checkpoint); when an update
loses an actor or
reconfigure() is called, the gang is drained, re-spawned at the new
world size (bounded by elastic_reform_timeout_s, stepping down toward
elastic_min_learners when capacity is short), the cached state is
re-replicated over the new mesh (reshard: each rank re-slices its data
shard by the new world), and the update is retried — with the same
elastic.* span sequence + reconfiguration metrics as the train plane
(train/elastic.py).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional

import numpy as np

logger = logging.getLogger(__name__)


class _MeshLearnerActor:
    """One rank of the learner gang; must run in a fresh worker process
    (jax.distributed can only initialize before any other jax use, which
    the gang's unique runtime-env pool key guarantees)."""

    def __init__(self, factory: Callable[[], Any], coordinator: str,
                 world: int, rank: int, seed: int, gang_id: str = ""):
        import os

        import jax
        # Heartbeat sidecar BEFORE jax.distributed.initialize: the
        # rendezvous itself is a collective that can wedge (a peer
        # SIGSTOPped mid-join), and the supervisor can only see that
        # through beats that started first.
        self._heartbeat = None
        if gang_id:
            from ray_tpu.train.heartbeat import HeartbeatSender
            hb = HeartbeatSender(gang_id, rank)
            if hb.start():
                self._heartbeat = hb
                hb.set_phase("rendezvous")
        # Honor an explicit platform pin (the chip-free test ladder sets
        # JAX_PLATFORMS=cpu): device plugins can re-assert themselves over
        # the env var, so pin through jax.config like tests/conftest.py.
        plat = os.environ.get("JAX_PLATFORMS")
        if plat:
            jax.config.update("jax_platforms", plat)
        if plat == "cpu":
            # XLA's CPU backend refuses cross-process computations
            # ("Multiprocess computations aren't implemented on the CPU
            # backend") unless collectives go through gloo — required
            # for the chip-free ladder to exercise real gang updates.
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
            except Exception:  # noqa: BLE001 - older jax: no such knob
                pass
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=world, process_id=rank)
        self.rank = rank
        self.world = world
        self.learner = factory()
        self.learner.build_distributed(seed=seed)
        if self._heartbeat is not None:
            self._heartbeat.set_phase("ready")

    def ping(self) -> str:
        return "pong"

    def _local_shard(self, batch: Dict[str, np.ndarray]
                     ) -> Dict[str, np.ndarray]:
        """Equal per-rank slices along each column's data axis (truncating
        the remainder so every rank runs identical jit step counts).
        Multi-agent batches are nested {module_id: {col: array}}; each
        module's rows shard independently so every rank holds a static
        per-module shape (the lane→module split is deterministic, so all
        ranks agree on each module's row count)."""
        if batch and all(isinstance(v, dict) for v in batch.values()):
            return {mid: self._local_shard(sub)
                    for mid, sub in batch.items()}
        first = next(iter(batch))
        axis = self.learner.data_axis_for(first)
        n = batch[first].shape[axis]
        per = n // self.world
        out = {}
        for k, v in batch.items():
            a = self.learner.data_axis_for(k)
            sl = [slice(None)] * v.ndim
            sl[a] = slice(self.rank * per, (self.rank + 1) * per)
            out[k] = v[tuple(sl)]
        return out

    def update(self, batch, minibatch_size, num_iters, seed):
        if self._heartbeat is not None:
            # the update round is the supervisor's step unit
            self._heartbeat.note_step()
            self._heartbeat.set_phase("update")
        return self.learner.update_distributed(
            self._local_shard(batch), minibatch_size, num_iters, seed)

    def additional_update(self, **kw):
        return self.learner.additional_update(**kw)

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, w):
        self.learner.set_weights(w)

    def get_state(self):
        return self.learner.get_state()

    def set_state(self, s):
        self.learner.set_state(s)


from ray_tpu.train.elastic import free_port as _free_port


class LearnerGroup:
    # wedge supervisor cadence (mirrors train/backend_executor.py)
    WEDGE_POLL_S = 1.0
    WEDGE_HB_REFRESH_S = 2.0

    def __init__(self, learner_factory: Callable[[], Any],
                 num_learners: int = 0, seed: int = 0, *,
                 elastic_min_learners: Optional[int] = None,
                 elastic_reform_timeout_s: float = 60.0,
                 state_refresh_every: int = 1,
                 step_deadline_s: Optional[float] = None):
        if step_deadline_s is not None and step_deadline_s <= 0:
            raise ValueError(
                f"step_deadline_s must be > 0, got {step_deadline_s}")
        self._num_learners = num_learners   # achieved world size
        self._target_learners = num_learners  # what re-forms aim for
        self._factory = learner_factory
        self._seed = seed
        self._elastic_min = elastic_min_learners
        self._reform_timeout_s = elastic_reform_timeout_s
        # gang heartbeat channel id; fresh per formation (_spawn_gang)
        self._gang_uid: Optional[str] = None
        # per-step wedge deadline — enforced only for elastic gangs
        # (explicit step_deadline_s, else auto-calibrated from trailing
        # update times; runtime-tunable via metrics_configure)
        self._step_deadline = None
        if elastic_min_learners is not None:
            from ray_tpu.train.heartbeat import StepDeadline
            self._step_deadline = StepDeadline(step_deadline_s)
        # How many updates between durable-cache refreshes. The cache
        # fetch pulls the FULL params+opt state from rank 0 to the
        # driver, so for large models every-update (the default, exact
        # continuity) can dominate step time; N>1 trades that cost for
        # losing up to N-1 updates when a reconfiguration falls back to
        # an older cache (the caller retries only the failed update).
        if state_refresh_every < 1:
            raise ValueError("state_refresh_every must be >= 1")
        self._state_refresh_every = state_refresh_every
        self._updates_since_refresh = 0
        self._ckpt_state: Optional[Dict[str, Any]] = None
        self._tracker = None
        if elastic_min_learners is not None:
            if num_learners == 0:
                raise ValueError(
                    "elastic_min_learners requires a remote gang "
                    "(num_learners >= 1)")
            if not (1 <= elastic_min_learners <= num_learners):
                raise ValueError(
                    f"elastic_min_learners={elastic_min_learners} not in "
                    f"[1, num_learners={num_learners}]")
            from ray_tpu.train.elastic import ReconfigTracker
            self._tracker = ReconfigTracker("learner")
        if num_learners == 0:
            self._local = learner_factory()
            self._local.build(seed=seed)
            self._actors: List[Any] = []
            return
        self._local = None
        self._actors = self._spawn_gang(num_learners)
        if self._tracker is not None:
            # the gang's durable fallback until the first update lands
            self._ckpt_state = self.get_state()

    @property
    def elastic(self) -> bool:
        return self._tracker is not None

    def _spawn_gang(self, world: int) -> List[Any]:
        """Spawn + rendezvous one gang generation of `world` fresh
        processes. Each formation gets its OWN runtime-env pool key
        (train.elastic.gang_runtime_env): jax.distributed must
        initialize before any other jax use, so a re-form can never
        reuse a previous generation's processes."""
        import uuid

        import ray_tpu
        from ray_tpu.train.elastic import gang_runtime_env
        gang_env = gang_runtime_env("RAY_TPU_LEARNER_GANG")
        coordinator = f"127.0.0.1:{_free_port()}"
        # fresh heartbeat channel per generation: stale rows from a
        # torn-down gang never shadow the new one
        self._gang_uid = f"learner:{uuid.uuid4().hex[:8]}"
        actor_cls = ray_tpu.remote(_MeshLearnerActor)
        actors = [
            actor_cls.options(num_cpus=1, runtime_env=gang_env).remote(
                self._factory, coordinator, world, rank, self._seed,
                self._gang_uid)
            for rank in range(world)
        ]
        # Barrier on gang readiness (rank 0 hosts the coordinator; all
        # ranks block in jax.distributed.initialize until every peer is
        # up — mirror of the reference's process-group rendezvous). On
        # failure the attempt's actors must die HERE: the caller's
        # _kill_gang only sees self._actors, and a leaked attempt would
        # sit blocked in jax.distributed holding its CPUs — making every
        # smaller world size infeasible too.
        try:
            ray_tpu.get([a.ping.remote() for a in actors],
                        timeout=self._reform_timeout_s
                        if self.elastic else 300)
        except BaseException:
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:  # noqa: BLE001 - actor already dead
                    pass
            raise
        return actors

    def __len__(self) -> int:
        return max(1, self._num_learners)

    # ---- elastic reconfiguration ------------------------------------
    def reconfigure(self, num_learners: Optional[int] = None,
                    reason: str = "manual") -> int:
        """Re-form the gang at `num_learners` (default: the target
        world size) from the cached state; returns the achieved world
        size. An explicit `num_learners` also becomes the new target.
        Elastic gangs only."""
        if not self.elastic:
            raise RuntimeError("reconfigure() requires elastic mode "
                               "(elastic_min_learners)")
        if num_learners is not None:
            # validate BEFORE persisting: a rejected target must not
            # poison later worker_death recoveries
            if num_learners < self._elastic_min:
                raise ValueError(
                    f"target {num_learners} below elastic_min_learners="
                    f"{self._elastic_min}")
            self._target_learners = num_learners
        return self._elastic_reconfigure(
            reason, target=num_learners or self._target_learners)

    def _elastic_reconfigure(self, reason: str, target: int) -> int:
        import ray_tpu
        if not (self._elastic_min <= target):
            raise ValueError(
                f"target {target} below elastic_min_learners="
                f"{self._elastic_min}")
        rec = self._tracker.start(reason,
                                  world_size=len(self._actors))
        try:
            with rec.phase("drain"):
                self._kill_gang()
            with rec.phase("checkpoint") as attrs:
                attrs["cached"] = self._ckpt_state is not None
            achieved: Optional[int] = None
            with rec.phase("reform"):
                # step down toward the min when capacity is short; each
                # attempt is bounded by elastic_reform_timeout_s
                last_err: Optional[BaseException] = None
                for world in range(target, self._elastic_min - 1, -1):
                    try:
                        self._actors = self._spawn_gang(world)
                        achieved = world
                        break
                    except Exception as e:  # noqa: BLE001 - rendezvous
                        last_err = e        # timeout / spawn failure
                        self._kill_gang()
                if achieved is None:
                    raise RuntimeError(
                        f"elastic learner re-form infeasible: no world "
                        f"size in [{self._elastic_min}, {target}] "
                        f"became ready within "
                        f"{self._reform_timeout_s:.0f}s per attempt "
                        f"({last_err!r})")
            self._num_learners = achieved
            with rec.phase("reshard", world_size=achieved):
                if self._ckpt_state is not None:
                    ray_tpu.get(
                        [a.set_state.remote(self._ckpt_state)
                         for a in self._actors], timeout=600)
            with rec.phase("resume"):
                pass  # the caller's retried update is the resume
            rec.finish(achieved)
            return achieved
        except BaseException as e:
            rec.abort(e)
            raise

    def _kill_gang(self) -> None:
        import ray_tpu
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001 - actor already dead
                pass
        self._actors = []
        if self._gang_uid is not None:
            from ray_tpu.train import heartbeat as hb
            from ray_tpu.train.elastic import _core_worker_or_none
            cw = _core_worker_or_none()
            if cw is not None:
                hb.clear_gang(cw._gcs.call, self._gang_uid)
            self._gang_uid = None

    # ---- updates ----------------------------------------------------
    def update(self, batch: Dict[str, np.ndarray],
               minibatch_size: Optional[int] = None,
               num_iters: int = 1, seed: int = 0) -> Dict[str, float]:
        from ray_tpu._private import goodput
        if self._local is not None:
            # the local learner computes in-process: sentinel compile
            # events on this thread re-attribute warmup out of the
            # productive window
            with goodput.bucket(goodput.PRODUCTIVE):
                return self._local.update(batch, minibatch_size,
                                          num_iters, seed)
        try:
            with goodput.bucket(goodput.PRODUCTIVE):
                return self._update_remote(batch, minibatch_size,
                                           num_iters, seed)
        except Exception as e:  # noqa: BLE001 - actor death mid-update
            from ray_tpu.exceptions import RayTaskError
            from ray_tpu.train.backend_executor import GangWedgedError
            if not self.elastic or isinstance(e, RayTaskError):
                # a RayTaskError means the update RAN and raised — a
                # deterministic application error that a gang re-form
                # would only replay (and miscount as a worker_death
                # reconfiguration); only infrastructure failures
                # (actor death, lost worker, timeout, wedge) reconfigure
                raise
            logger.warning(
                "elastic learner gang update failed (%r); "
                "reconfiguring and retrying", e)
            # aim back at the TARGET, not the achieved size: a gang
            # that degraded to 3/4 must try for 4 again when capacity
            # returns, not ratchet down toward the minimum
            self._elastic_reconfigure(
                "wedge" if isinstance(e, GangWedgedError)
                else "worker_death",
                target=self._target_learners)
            with goodput.bucket(goodput.PRODUCTIVE):
                return self._update_remote(batch, minibatch_size,
                                           num_iters, seed)

    def _update_remote(self, batch, minibatch_size, num_iters, seed):
        import ray_tpu
        # Same full batch + same seed to every rank: each slices its own
        # equal shard and all ranks enter the jitted collective step the
        # same number of times.
        refs = [a.update.remote(batch, minibatch_size, num_iters, seed)
                for a in self._actors]
        if self.elastic:
            # wedge-aware wait: a rank SIGSTOPped inside the psum
            # otherwise blocks every peer for the full 600s get
            stats = self._await_update(refs, timeout=600)
        else:
            stats = ray_tpu.get(refs, timeout=600)
        # Scalars mean-reduce across ranks; array stats (per-sample TD
        # errors + their batch indexes) concatenate in rank order — each
        # rank reported its own shard of the global batch.
        out: Dict[str, Any] = {}
        for k in stats[0]:
            if getattr(stats[0][k], "ndim", 0):
                out[k] = np.concatenate([np.asarray(s[k]) for s in stats])
            else:
                out[k] = float(np.mean([s[k] for s in stats]))
        if self.elastic:
            # refresh the durable fallback: the state every rank holds
            # after this (replicated) step — what a reconfiguration
            # reshards from (paced by state_refresh_every for large
            # models; a failed fetch just leaves the older cache)
            self._updates_since_refresh += 1
            if self._updates_since_refresh >= self._state_refresh_every:
                try:
                    self._ckpt_state = ray_tpu.get(
                        self._actors[0].get_state.remote(), timeout=600)
                    self._updates_since_refresh = 0
                except Exception:  # noqa: BLE001 - the NEXT update's
                    pass           # failure path uses the older cache
        return out

    # ---- collective-wedge supervisor (train/heartbeat.py) -----------
    def _await_update(self, refs: List[Any], timeout: float
                      ) -> List[Any]:
        """Await one update round with the wedge trip armed — the
        learner-plane mirror of BackendExecutor._await_round. Short
        wait slices; between slices the supervisor refreshes the gang
        heartbeat table (which also carries the runtime step-deadline
        override) and, once the deadline expires, checks staleness.
        Two-factor trip: deadline expired AND >= 1 stale heartbeat —
        every-rank-fresh-but-slow keeps waiting. On a trip the wedged
        pids are hard-killed via their node managers and
        GangWedgedError routes into _elastic_reconfigure with
        reason="wedge". Round times feed the deadline calibrator."""
        import time as _time

        import ray_tpu
        from ray_tpu.train import heartbeat as hb
        from ray_tpu.train.backend_executor import GangWedgedError
        t0 = _time.monotonic()
        hb_next = 0.0
        override: Optional[float] = None
        while True:
            ready, pending = ray_tpu.wait(
                refs, num_returns=len(refs), timeout=self.WEDGE_POLL_S)
            if not pending:
                stats = ray_tpu.get(  # graftlint: disable=RT002
                    refs, timeout=60)
                self._step_deadline.observe(_time.monotonic() - t0)
                return stats
            now = _time.monotonic()
            if now - t0 > timeout:
                raise TimeoutError(
                    f"no learner update round within {timeout:.0f}s")
            if now < hb_next:
                continue
            hb_next = now + self.WEDGE_HB_REFRESH_S
            reply = self._query_heartbeats()
            if reply is None:
                continue
            if reply.get("step_deadline_override_s") is not None:
                override = reply["step_deadline_override_s"]
            deadline = self._step_deadline.current(override)
            if deadline is None or now - t0 < deadline:
                continue
            from ray_tpu._private.config import Config
            stale = hb.stale_ranks(reply,
                                   Config.watchdog_gang_heartbeat_s)
            if not stale:
                continue  # slow but every rank alive: keep waiting
            from ray_tpu._private import spans
            cls = hb.classify_wedge(reply, stale)
            spans.instant(
                "elastic.wedge_detect", gang=self._gang_uid,
                classification=cls["kind"],
                ranks=",".join(str(r) for r in cls["ranks"]),
                nodes=",".join(n[:12] for n in cls["nodes"]),
                deadline_s=round(deadline, 3),
                waited_s=round(now - t0, 3))
            logger.error(
                "elastic learner: step deadline %.1fs expired after "
                "%.1fs with stale heartbeat(s) from rank(s) %s (%s); "
                "hard-killing wedged processes and re-forming",
                deadline, now - t0, cls["ranks"], cls["kind"])
            killed = hb.hard_kill_ranks(stale)
            raise GangWedgedError(
                f"learner rank(s) {cls['ranks']} wedged mid-update "
                f"({cls['kind']}): step deadline {deadline:.1f}s "
                f"expired with heartbeats "
                f"{[round(r['age_s'], 1) for r in stale]}s stale; "
                f"hard-killed ranks {killed} via their node managers")

    def _query_heartbeats(self) -> Optional[Dict[str, Any]]:
        if self._gang_uid is None:
            return None
        from ray_tpu.train import heartbeat as hb
        from ray_tpu.train.elastic import _core_worker_or_none
        cw = _core_worker_or_none()
        if cw is None:
            return None
        try:
            return hb.query_gang(cw._gcs.call, self._gang_uid)
        except Exception:  # noqa: BLE001 - GCS hiccup: retry next slice
            return None

    def additional_update(self, **kwargs) -> Dict[str, Any]:
        if self._local is not None:
            return self._local.additional_update(**kwargs)
        import ray_tpu
        outs = ray_tpu.get(
            [a.additional_update.remote(**kwargs) for a in self._actors],
            timeout=120)
        return outs[0]

    # ---- weights ----------------------------------------------------
    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        import ray_tpu
        return ray_tpu.get(self._actors[0].get_weights.remote(),
                           timeout=600)

    def set_weights(self, w) -> None:
        if self._local is not None:
            self._local.set_weights(w)
            return
        import ray_tpu
        ray_tpu.get([a.set_weights.remote(w) for a in self._actors],
                    timeout=600)

    def get_state(self):
        if self._local is not None:
            return self._local.get_state()
        import ray_tpu
        return ray_tpu.get(self._actors[0].get_state.remote(), timeout=600)

    def set_state(self, state) -> None:
        if self._local is not None:
            self._local.set_state(state)
            return
        import ray_tpu
        ray_tpu.get([a.set_state.remote(state) for a in self._actors],
                    timeout=600)
        if self.elastic:
            self._ckpt_state = state

    def shutdown(self) -> None:
        self._kill_gang()
        if self._tracker is not None:
            self._tracker.close()
