"""LearnerGroup: one local learner or a gang of learner actors.

reference parity: rllib/core/learner/learner_group.py:63 — local mode
(num_learners=0, learner in-process: the CartPole north-star config) or
remote mode where learner actors are spawned over Train's worker-group
machinery (learner_group.py:103-115 reuses BackendExecutor) and updates
run data-parallel. The reference syncs gradients with torch DDP
(torch_learner.py:378-390); here remote learners each update on their
batch shard and the group averages the resulting *weights* host-side
each round (equivalent to averaged-gradient DDP for equal shards under
linear optimizers, and the standard host-RAM path for CPU learners —
on a TPU pod the learners instead share one ICI mesh via
jax.distributed, where psum rides the interconnect, see
ray_tpu.train.JaxConfig).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np


class LearnerGroup:
    def __init__(self, learner_factory: Callable[[], Any],
                 num_learners: int = 0, seed: int = 0):
        self._num_learners = num_learners
        if num_learners == 0:
            self._local = learner_factory()
            self._local.build(seed=seed)
            self._actors: List[Any] = []
        else:
            import ray_tpu

            @ray_tpu.remote
            class LearnerActor:
                def __init__(self, factory, seed):
                    self.learner = factory()
                    self.learner.build(seed=seed)

                def update(self, batch, minibatch_size, num_iters, seed):
                    return self.learner.update(
                        batch, minibatch_size, num_iters, seed)

                def additional_update(self, **kw):
                    return self.learner.additional_update(**kw)

                def get_weights(self):
                    return self.learner.get_weights()

                def set_weights(self, w):
                    self.learner.set_weights(w)

                def get_state(self):
                    return self.learner.get_state()

                def set_state(self, s):
                    self.learner.set_state(s)

            self._local = None
            self._actors = [LearnerActor.options(num_cpus=1).remote(
                learner_factory, seed) for _ in range(num_learners)]
            # all replicas must start from identical weights
            import ray_tpu as rt
            w0 = rt.get(self._actors[0].get_weights.remote(), timeout=120)
            rt.get([a.set_weights.remote(w0) for a in self._actors[1:]],
                   timeout=120)

    def __len__(self) -> int:
        return max(1, self._num_learners)

    # ---- updates ----------------------------------------------------
    def update(self, batch: Dict[str, np.ndarray],
               minibatch_size: Optional[int] = None,
               num_iters: int = 1, seed: int = 0) -> Dict[str, float]:
        if self._local is not None:
            return self._local.update(batch, minibatch_size, num_iters,
                                      seed)
        import jax
        import ray_tpu

        shards = _shard_batch(batch, len(self._actors))
        stats = ray_tpu.get([
            a.update.remote(s, minibatch_size, num_iters, seed + i)
            for i, (a, s) in enumerate(zip(self._actors, shards))
        ], timeout=600)
        # average replica weights (see module docstring)
        weights = ray_tpu.get(
            [a.get_weights.remote() for a in self._actors], timeout=600)
        mean_w = jax.tree.map(
            lambda *xs: np.mean(np.stack(xs), axis=0), *weights)
        ray_tpu.get([a.set_weights.remote(mean_w) for a in self._actors],
                    timeout=600)
        return {k: float(np.mean([s[k] for s in stats]))
                for k in stats[0]}

    def additional_update(self, **kwargs) -> Dict[str, Any]:
        if self._local is not None:
            return self._local.additional_update(**kwargs)
        import ray_tpu
        outs = ray_tpu.get(
            [a.additional_update.remote(**kwargs) for a in self._actors],
            timeout=120)
        return outs[0]

    # ---- weights ----------------------------------------------------
    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        import ray_tpu
        return ray_tpu.get(self._actors[0].get_weights.remote(),
                           timeout=600)

    def set_weights(self, w) -> None:
        if self._local is not None:
            self._local.set_weights(w)
            return
        import ray_tpu
        ray_tpu.get([a.set_weights.remote(w) for a in self._actors],
                    timeout=600)

    def get_state(self):
        if self._local is not None:
            return self._local.get_state()
        import ray_tpu
        return ray_tpu.get(self._actors[0].get_state.remote(), timeout=600)

    def set_state(self, state) -> None:
        if self._local is not None:
            self._local.set_state(state)
            return
        import ray_tpu
        ray_tpu.get([a.set_state.remote(state) for a in self._actors],
                    timeout=600)

    def shutdown(self) -> None:
        import ray_tpu
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001
                pass
        self._actors = []


def _shard_batch(batch: Dict[str, np.ndarray], n: int
                 ) -> List[Dict[str, np.ndarray]]:
    size = len(batch["obs"])
    idx = np.array_split(np.arange(size), n)
    return [{k: v[i] for k, v in batch.items()} for i in idx]
