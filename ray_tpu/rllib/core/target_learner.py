"""PolyakTargetLearner: shared target-network scaffolding.

SAC and TD3 both keep polyak-averaged target copies of (a subtree of)
their params, split an rng per jitted update, and (de)replicate targets
through checkpoints — this base holds that once (the reference keeps
the equivalent in each policy class; here it's one mixin over the
jax Learner engine).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu.rllib.core.learner import Learner


class PolyakTargetLearner(Learner):
    """Subclasses set `target_keys` (None = the whole param tree) and
    read extra["target"] / extra["rng"] in compute_loss; the owning
    algorithm calls additional_update(polyak=True) after each gradient
    step."""

    target_keys: Optional[List[str]] = None
    rng_salt: int = 0

    def build(self, seed: int = 0) -> None:
        super().build(seed)
        self._post_build(seed)

    def build_distributed(self, seed: int = 0) -> None:
        super().build_distributed(seed)
        self._post_build(seed)

    def _target_subtree(self, params):
        if self.target_keys is None:
            return params
        return {k: params[k] for k in self.target_keys}

    def _post_build(self, seed: int) -> None:
        import jax
        import jax.numpy as jnp
        with self._state_lock:
            self._target = jax.tree.map(
                jnp.copy, self._target_subtree(self._params))
        self._rng = jax.random.PRNGKey(seed + self.rng_salt)
        tau = self.config.tau

        def polyak(target, params):
            return jax.tree.map(
                lambda t, p: (1.0 - tau) * t + tau * p, target,
                self._target_subtree(params))

        # donate the old target: the update rebinds self._target to the
        # result, so XLA can reuse the MB-scale buffer in place instead
        # of allocating a fresh tree per update (CPU does not donate)
        donate = () if jax.default_backend() == "cpu" else (0,)
        self._polyak = jax.jit(polyak, donate_argnums=donate)

    def extra_inputs(self) -> Dict[str, Any]:
        import jax
        self._rng, sub = jax.random.split(self._rng)
        return {"target": self._target, "rng": sub}

    def additional_update(self, *, polyak: bool = True,
                          **kw) -> Dict[str, Any]:
        """Polyak target update; also absorbs the base replay loop's
        periodic update_target=True (a hard sync would fight
        tau-averaging)."""
        if polyak:
            with self._state_lock:
                self._target = self._polyak(self._target, self._params)
        return {}

    def get_state(self) -> Dict[str, Any]:
        import jax
        state = super().get_state()
        with self._state_lock:
            state["target"] = jax.device_get(self._target)
        return state

    def set_state(self, state: Dict[str, Any]) -> None:
        super().set_state(state)
        import jax
        import jax.numpy as jnp
        with self._state_lock:
            if getattr(self, "_distributed", False):
                self._target = jax.tree.map(self._replicate_host,
                                            state["target"])
            else:
                self._target = jax.tree.map(jnp.asarray,
                                            state["target"])


class ContinuousReplayAlgoMixin:
    """Algorithm-side hooks shared by SAC/TD3 over DQN's replay loop:
    no epsilon push by default (these policies explore their own way —
    TD3 overrides _before_sample to push its noise scale instead), one
    gradient step per sampled env step by default, polyak after every
    update instead of periodic hard target syncs."""

    def _before_sample(self, stats: Dict[str, Any]) -> None:
        pass  # no epsilon; stochastic/noise exploration is in-policy

    def _training_intensity(self) -> float:
        cfg = self.config
        return (cfg.training_intensity
                if cfg.training_intensity is not None
                else float(cfg.train_batch_size))

    def _after_each_update(self) -> None:
        self.learner_group.additional_update(polyak=True)

    def _maybe_update_target(self) -> None:
        pass  # polyak per update replaces periodic hard syncs
