"""Learner: the gradient engine, one jitted update.

reference parity: rllib/core/learner/learner.py:231 (Learner ABC:
compute_loss / compute_gradients / postprocess_gradients /
apply_gradients / additional_update at :557,679,988,1042) and
TorchLearner (torch_learner.py:53). The torch stack splits those into
five framework methods because autograd is stateful; in jax the whole
minibatch update — loss, grad, clip, apply — is ONE pure jitted function,
so the TPU Learner exposes compute_loss (override per algorithm) and the
engine jits everything around it. Gradient clipping ≙ postprocess_
gradients; additional_update handles KL-coeff style schedules.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_tpu.rllib.core.rl_module import RLModule


class Learner:
    def __init__(self, module: RLModule, config):
        self.module = module
        self.config = config
        self._params = None
        self._opt_state = None
        self._optimizer = None
        self._update_fn = None
        # Serializes updates against weight reads: the jitted update
        # DONATES the params buffer, so a concurrent device_get (e.g. an
        # async IMPALA driver syncing weights while the learner thread
        # trains) would read a deleted array.
        self._state_lock = threading.Lock()
        # mutable non-jitted state for additional_update (e.g. kl coeff)
        self.curr_kl_coeff = getattr(config, "kl_coeff", 0.0)

    # ---- build ------------------------------------------------------
    def build(self, seed: int = 0) -> None:
        import jax
        import optax

        # params/opt_state are lock-guarded everywhere else (a weight
        # sync racing an update must not tear the pytree); build() is
        # nominally pre-concurrency but is a public entry point, so it
        # takes the same lock rather than asserting callers sequence it
        with self._state_lock:
            self._params = self.module.init_params(
                jax.random.PRNGKey(seed))
        clip = getattr(self.config, "grad_clip", None)
        chain = []
        if clip:
            chain.append(optax.clip_by_global_norm(clip))
        chain.append(optax.adam(self.config.lr))
        self._optimizer = optax.chain(*chain)
        with self._state_lock:
            self._opt_state = self._optimizer.init(self._params)

        def update(params, opt_state, batch, extra):
            def loss_wrap(p):
                loss, stats = self.compute_loss(p, batch, extra)
                return loss, stats

            (loss, stats), grads = jax.value_and_grad(
                loss_wrap, has_aux=True)(params)
            updates, opt_state = self._optimizer.update(
                grads, opt_state, params)
            updates = self.postprocess_updates(updates, extra)
            params = optax.apply_updates(params, updates)
            stats = dict(stats)
            stats["total_loss"] = loss
            stats["grad_norm"] = optax.global_norm(grads)
            return params, opt_state, stats

        def update_idx(params, opt_state, batch, idx, extra):
            # one minibatch with its gather fused into the program (only
            # the small idx crosses the host boundary per step); idx may
            # be a per-module dict for multi-agent batches
            if isinstance(idx, dict):
                mb = {mid: jax.tree.map(lambda v: v[idx[mid]],
                                        batch[mid])
                      for mid in idx}
            else:
                mb = jax.tree.map(lambda v: v[idx], batch)
            return update(params, opt_state, mb, extra)

        def sweep(params, opt_state, batch, idx_mat, extra):
            # The WHOLE minibatch-SGD sweep (num_epochs x minibatches) as
            # one lax.scan program: one XLA dispatch per Learner.update
            # instead of one per minibatch — dispatch latency (notably
            # over a TPU tunnel) would otherwise dominate small updates.
            # idx_mat: [steps, minibatch] row indices into batch.
            def body(carry, idx):
                p, o = carry
                p, o, st = update_idx(p, o, batch, idx, extra)
                return (p, o), st

            (params, opt_state), stats_seq = jax.lax.scan(
                body, (params, opt_state), idx_mat)
            return params, opt_state, stats_seq

        self._update_fn = jax.jit(update, donate_argnums=(0, 1))
        self._sweep_fn = jax.jit(sweep, donate_argnums=(0, 1))
        self._update_idx_fn = jax.jit(update_idx, donate_argnums=(0, 1))

    @staticmethod
    def _use_scan_sweep() -> bool:
        """Whether the minibatch-SGD sweep runs as ONE lax.scan program
        (best where dispatch latency dominates — TPU, notably over a
        tunnel) or as a python loop of per-minibatch jit calls (XLA:CPU
        emits convolutions inside while-loop bodies through a slow
        generic path — measured ~50x slower than the same update
        outside the loop — so CPU defaults to the loop). Override with
        RAY_TPU_LEARNER_SWEEP=scan|loop."""
        import os

        import jax
        forced = os.environ.get("RAY_TPU_LEARNER_SWEEP", "").lower()
        if forced in ("scan", "loop"):
            return forced == "scan"
        return jax.default_backend() != "cpu"

    # ---- distributed (mesh gang) build ------------------------------
    def data_axis_for(self, key: str) -> int:
        """Which axis of a batch column is the data-parallel axis (row
        batches → 0; time-major IMPALA sequences override to 1)."""
        return 0

    def build_distributed(self, seed: int = 0) -> None:
        """Build after jax.distributed.initialize: params/opt replicated
        over a 'data' mesh spanning every process, batches sharded along
        the data axis. Gradients all-reduce over ICI because the jitted
        global-mean loss contracts over the sharded batch axis with
        replicated params — the DDP-equivalent the reference gets from
        torch DDP (torch_learner.py:378-390), with XLA inserting the
        psum instead of a wrapper module."""
        import jax
        import optax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        devices = np.array(jax.devices())
        self._mesh = Mesh(devices, ("data",))
        self._rep = NamedSharding(self._mesh, P())

        host_params = self.module.init_params(jax.random.PRNGKey(seed))

        def _replicate(x):
            return jax.make_array_from_callback(
                np.shape(x), self._rep, lambda idx: np.asarray(x)[idx])

        self._replicate_host = _replicate
        # same locking rationale as build(): public entry, shared state
        with self._state_lock:
            self._params = jax.tree.map(_replicate, host_params)
        clip = getattr(self.config, "grad_clip", None)
        chain = []
        if clip:
            chain.append(optax.clip_by_global_norm(clip))
        chain.append(optax.adam(self.config.lr))
        self._optimizer = optax.chain(*chain)
        with self._state_lock:
            self._opt_state = jax.tree.map(
                _replicate, self._optimizer.init(host_params))

        def update(params, opt_state, batch, extra):
            def loss_wrap(p):
                return self.compute_loss(p, batch, extra)

            (loss, stats), grads = jax.value_and_grad(
                loss_wrap, has_aux=True)(params)
            updates, opt_state = self._optimizer.update(
                grads, opt_state, params)
            updates = self.postprocess_updates(updates, extra)
            params = optax.apply_updates(params, updates)
            stats = dict(stats)
            stats["total_loss"] = loss
            stats["grad_norm"] = optax.global_norm(grads)
            return params, opt_state, stats

        self._update_fn = jax.jit(
            update, donate_argnums=(0, 1),
            out_shardings=(self._rep, self._rep, self._rep))
        self._distributed = True

    def _make_global_batch(self, local: Dict[str, np.ndarray]
                           ) -> Dict[str, Any]:
        """Process-local shard → global jax.Arrays sharded on 'data'."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        out = {}
        for k, v in local.items():
            axis = self.data_axis_for(k)
            spec = P(*([None] * axis), "data")
            out[k] = jax.make_array_from_process_local_data(
                NamedSharding(self._mesh, spec), np.asarray(v))
        return out

    def update_distributed(self, local_batch: Dict[str, np.ndarray],
                           minibatch_size: Optional[int] = None,
                           num_iters: int = 1,
                           seed: int = 0) -> Dict[str, float]:
        """DDP-style minibatch SGD: every process runs the SAME number of
        jitted steps (collectives wedge otherwise); each step's global
        minibatch is the union of per-process local samples."""
        import jax

        first = next(iter(local_batch))
        axis = self.data_axis_for(first)
        n = local_batch[first].shape[axis]
        nprocs = max(1, jax.process_count())
        local_mb = max(1, (minibatch_size or n * nprocs) // nprocs)
        rng = np.random.default_rng(seed)
        stats: Dict[str, Any] = {}
        count = 0
        for _ in range(num_iters):
            perm = rng.permutation(n)
            for start in range(0, n - local_mb + 1, local_mb):
                idx = perm[start:start + local_mb]
                mb = {k: np.take(v, idx, axis=self.data_axis_for(k))
                      for k, v in local_batch.items()}
                gb = self._make_global_batch(mb)
                with self._state_lock:
                    self._params, self._opt_state, st = self._update_fn(
                        self._params, self._opt_state, gb,
                        self.extra_inputs())
                count += 1
                self._accumulate(stats, st)
        if count == 0:  # batch smaller than one minibatch: single step
            gb = self._make_global_batch(local_batch)
            with self._state_lock:
                self._params, self._opt_state, st = self._update_fn(
                    self._params, self._opt_state, gb, self.extra_inputs())
            count = 1
            stats = {}
            self._accumulate(stats, st)
        return self._finalize(stats, count)

    # ---- algorithm contract ----------------------------------------
    def compute_loss(self, params, batch: Dict[str, Any],
                     extra: Dict[str, Any]) -> Tuple[Any, Dict[str, Any]]:
        raise NotImplementedError

    def additional_update(self, **kwargs) -> Dict[str, Any]:
        return {}

    def postprocess_updates(self, updates, extra):
        """Inside-jit hook between optimizer.update and apply_updates
        (e.g. TD3 masks the actor subtree on non-delayed steps —
        zeroing the LOSS alone wouldn't stop Adam momentum from moving
        the params). Default: identity."""
        return updates

    def extra_inputs(self) -> Dict[str, Any]:
        """Scalars threaded into the jitted loss (kl coeff etc.)."""
        return {}

    def _stage_weights_async(self) -> None:
        """Start async device→host copies of the params so a later
        get_weights (weight broadcast to samplers) finds the data already
        landed instead of paying one blocking round trip per leaf —
        measured 0.6-0.75 s/call over the TPU tunnel without staging."""
        import jax
        for leaf in jax.tree.leaves(self._params):
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()

    # ---- stats ------------------------------------------------------
    @staticmethod
    def _accumulate(stats: Dict[str, Any], st: Dict[str, Any]) -> None:
        """Scalar stats average over minibatches; array-valued stats
        (e.g. per-sample TD errors for prioritized replay) keep the last
        minibatch's values."""
        for k, v in st.items():
            if getattr(v, "ndim", 0):
                stats[k] = np.asarray(v)
            else:
                stats[k] = stats.get(k, 0.0) + float(v)

    @staticmethod
    def _finalize(stats: Dict[str, Any], count: int) -> Dict[str, Any]:
        return {k: (v if isinstance(v, np.ndarray) else v / count)
                for k, v in stats.items()}

    # ---- update loop ------------------------------------------------
    def update(self, batch: Dict[str, np.ndarray],
               minibatch_size: Optional[int] = None,
               num_iters: int = 1,
               seed: int = 0) -> Dict[str, float]:
        """Minibatch SGD over the batch (reference Learner.update /
        TorchLearner._update loop)."""
        import jax

        from ray_tpu._private import spans as _spans
        from ray_tpu.util import jax_sentinel
        with _spans.span("learner.update", num_iters=num_iters), \
                jax_sentinel.step_region("learner.update"):
            return self._update_impl(batch, minibatch_size, num_iters,
                                     seed, jax)

    def _update_impl(self, batch, minibatch_size, num_iters, seed, jax
                     ) -> Dict[str, float]:
        assert self._update_fn is not None, "call build() first"
        n = len(batch["obs"])
        minibatch_size = minibatch_size or n
        rng = np.random.default_rng(seed)
        # Row-index matrix for the scanned sweep: num_iters epochs of
        # shuffled minibatches, ragged tails dropped (stable jit shapes).
        rows = []
        for _ in range(num_iters):
            perm = rng.permutation(n)
            for start in range(0, n - minibatch_size + 1, minibatch_size):
                rows.append(perm[start:start + minibatch_size])
        if not rows:  # batch smaller than one minibatch: single step
            rows = [rng.permutation(n)]
        idx_mat = np.stack(rows).astype(np.int32)
        # One explicit host→device transfer of the whole batch up front
        # (dispatching jit calls with raw numpy batches can re-transfer
        # per-array, synchronously, on some backends).
        dev_batch = jax.device_put(batch)
        if self._use_scan_sweep():
            # ONE jitted lax.scan dispatch for the whole sweep
            with self._state_lock:
                self._params, self._opt_state, stats_seq = \
                    self._sweep_fn(self._params, self._opt_state,
                                   dev_batch, idx_mat,
                                   self.extra_inputs())
            return self._sweep_stats(jax.device_get(stats_seq))
        return self._loop_sweep(dev_batch, list(idx_mat))

    def _loop_sweep(self, dev_batch, step_indices) -> Dict[str, Any]:
        """Loop-sweep shared by single- and multi-agent update paths:
        one dispatch per minibatch, stats forced once at the end so the
        steps still pipeline."""
        import jax

        pending = []
        extra = self.extra_inputs()
        with self._state_lock:
            for idx in step_indices:
                self._params, self._opt_state, st = self._update_idx_fn(
                    self._params, self._opt_state, dev_batch, idx, extra)
                pending.append(st)
        host = jax.device_get(pending)  # single forcing point
        stacked = {k: np.stack([np.asarray(s[k]) for s in host])
                   for k in host[0]} if host else {}
        return self._sweep_stats(stacked)

    @staticmethod
    def _sweep_stats(stats_seq: Dict[str, Any]) -> Dict[str, Any]:
        """Stacked scan stats -> reported stats: scalars average over
        minibatches; array-valued stats (e.g. per-sample TD errors) keep
        the last minibatch's values — the _accumulate/_finalize
        contract."""
        out: Dict[str, Any] = {}
        for k, v in stats_seq.items():
            arr = np.asarray(v)
            if arr.ndim <= 1:
                out[k] = float(np.mean(arr))
            else:
                out[k] = arr[-1]
        return out

    # ---- weights ----------------------------------------------------
    def get_weights(self):
        import jax
        with self._state_lock:
            return jax.device_get(self._params)

    def set_weights(self, weights) -> None:
        with self._state_lock:
            if getattr(self, "_distributed", False):
                # Host pytrees must be re-laid-out as replicated global
                # arrays or the jitted update would see mixed shardings.
                import jax
                self._params = jax.tree.map(self._replicate_host, weights)
            else:
                self._params = weights

    def get_state(self) -> Dict[str, Any]:
        import jax
        with self._state_lock:
            return {"params": jax.device_get(self._params),
                    "opt_state": jax.device_get(self._opt_state),
                    "kl_coeff": self.curr_kl_coeff}

    def set_state(self, state: Dict[str, Any]) -> None:
        with self._state_lock:
            if getattr(self, "_distributed", False):
                import jax
                self._params = jax.tree.map(self._replicate_host,
                                            state["params"])
                self._opt_state = jax.tree.map(self._replicate_host,
                                               state["opt_state"])
            else:
                self._params = state["params"]
                self._opt_state = state["opt_state"]
            self.curr_kl_coeff = state.get("kl_coeff", self.curr_kl_coeff)


class MultiAgentLearnerMixin:
    """update() over a MultiAgentBatch {module_id: columns}.

    reference parity: Learner.update on a MultiAgentBatch
    (rllib/policy/sample_batch.py MultiAgentBatch; per-module losses in
    core/learner/learner.py compute_loss_for_module). Here one jitted
    lax.scan sweep steps every module together: per-module minibatch
    index vectors gather from per-module sub-batches (static shapes,
    since lane→module routing is fixed), the summed loss yields
    independent per-module gradients, and one optimizer updates the
    union params pytree."""

    def update_distributed(self, local_batch, minibatch_size=None,
                           num_iters=1, seed=0):
        """DDP-style minibatch SGD over a nested {module_id: columns}
        batch. Each rank holds its own per-module shard (equal sizes
        across ranks — _MeshLearnerActor._local_shard truncates), the
        shared seed makes every rank pick identical per-module index
        sets and step counts (collectives wedge otherwise), and each
        step's global minibatch is the per-module union of the local
        samples — per-agent modules shard across learner ranks with
        static per-rank shapes."""
        import jax

        n_m = {mid: len(next(iter(b.values())))
               for mid, b in local_batch.items()}
        empty = [mid for mid, n in n_m.items() if n == 0]
        if empty:
            raise ValueError(
                f"modules {empty} have no rows on this learner rank: "
                f"every rank needs >=1 row per module (grow "
                f"train_batch_size / rollout length or reduce "
                f"num_learners)")
        nprocs = max(1, jax.process_count())
        total = sum(n_m.values())
        local_target = max(1, (minibatch_size or total * nprocs)
                           // nprocs)
        mb_m = {mid: max(1, min(n, round(local_target * n / total)))
                for mid, n in n_m.items()}
        steps_per_epoch = max(1, min(n // mb_m[mid]
                                     for mid, n in n_m.items()))
        rng = np.random.default_rng(seed)
        stats: Dict[str, Any] = {}
        count = 0
        for _ in range(num_iters):
            perms = {mid: rng.permutation(n) for mid, n in n_m.items()}
            for s in range(steps_per_epoch):
                gb = {}
                for mid, b in local_batch.items():
                    idx = perms[mid][s * mb_m[mid]:(s + 1) * mb_m[mid]]
                    gb[mid] = self._make_global_batch(
                        {k: np.take(v, idx,
                                    axis=self.data_axis_for(k))
                         for k, v in b.items()})
                with self._state_lock:
                    self._params, self._opt_state, st = \
                        self._update_fn(self._params, self._opt_state,
                                        gb, self.extra_inputs())
                count += 1
                self._accumulate(stats, st)
        return self._finalize(stats, count)

    def update(self, batch, minibatch_size=None, num_iters=1, seed=0):
        import jax

        assert self._sweep_fn is not None, "call build() first"
        rng = np.random.default_rng(seed)
        n_m = {mid: len(b["obs"]) for mid, b in batch.items()}
        total = sum(n_m.values())
        minibatch_size = minibatch_size or total
        # Per-module minibatch sizes proportional to module rows; every
        # module steps the same number of scan iterations.
        mb_m = {mid: max(1, min(n, round(minibatch_size * n / total)))
                for mid, n in n_m.items()}
        steps_per_epoch = max(1, min(n // mb_m[mid]
                                     for mid, n in n_m.items()))
        rows: Dict[str, list] = {mid: [] for mid in n_m}
        for _ in range(num_iters):
            perms = {mid: rng.permutation(n) for mid, n in n_m.items()}
            for s in range(steps_per_epoch):
                for mid in n_m:
                    start = s * mb_m[mid]
                    rows[mid].append(
                        perms[mid][start:start + mb_m[mid]])
        idx_mat = {mid: np.stack(r).astype(np.int32)
                   for mid, r in rows.items()}
        dev_batch = jax.device_put(batch)
        if self._use_scan_sweep():
            with self._state_lock:
                self._params, self._opt_state, stats_seq = \
                    self._sweep_fn(self._params, self._opt_state,
                                   dev_batch, idx_mat,
                                   self.extra_inputs())
            return self._sweep_stats(jax.device_get(stats_seq))
        # loop sweep (Learner._loop_sweep): per-step dict idx
        n_steps = len(next(iter(idx_mat.values())))
        return self._loop_sweep(
            dev_batch,
            [{mid: m[s] for mid, m in idx_mat.items()}
             for s in range(n_steps)])
