"""RLModule: the policy/value network contract, jax-functional.

reference parity: rllib/core/rl_module/rl_module.py:229 — RLModule with
forward_exploration / forward_inference / forward_train. The reference
couples module objects to torch state; here modules are *stateless
describers*: params live in an explicit pytree (the Learner owns them),
every forward is a pure function — so the whole train step jits and the
EnvRunner can run the same module on CPU with device-put weights.

Output column names follow the reference's SampleBatch/Columns contract
(rllib/policy/sample_batch.py): actions, action_logp,
action_dist_inputs, vf_preds.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple


class RLModule:
    """Subclasses define the network; all methods are pure functions."""

    def init_params(self, key) -> Any:
        raise NotImplementedError

    def forward_train(self, params, batch: Dict[str, Any]
                      ) -> Dict[str, Any]:
        """-> {"action_dist_inputs": logits, "vf_preds": values}."""
        raise NotImplementedError

    def forward_exploration(self, params, batch: Dict[str, Any], key
                            ) -> Dict[str, Any]:
        """Stochastic acting: adds sampled actions + their logp."""
        out = self.forward_train(params, batch)
        dist = self.action_dist(out["action_dist_inputs"])
        actions, logp = dist.sample_and_logp(key)
        out["actions"] = actions
        out["action_logp"] = logp
        return out

    def forward_inference(self, params, batch: Dict[str, Any]
                          ) -> Dict[str, Any]:
        """Greedy acting."""
        out = self.forward_train(params, batch)
        dist = self.action_dist(out["action_dist_inputs"])
        out["actions"] = dist.mode()
        return out

    def action_dist(self, dist_inputs):
        raise NotImplementedError


class Categorical:
    """Categorical over logits [..., n]."""

    def __init__(self, logits):
        self.logits = logits

    def sample_and_logp(self, key) -> Tuple[Any, Any]:
        import jax
        actions = jax.random.categorical(key, self.logits, axis=-1)
        return actions, self.logp(actions)

    def logp(self, actions):
        import jax
        import jax.numpy as jnp
        logp_all = jax.nn.log_softmax(self.logits, axis=-1)
        return jnp.take_along_axis(
            logp_all, actions[..., None], axis=-1)[..., 0]

    def entropy(self):
        import jax
        import jax.numpy as jnp
        p = jax.nn.softmax(self.logits, axis=-1)
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return -jnp.sum(p * logp, axis=-1)

    def mode(self):
        import jax.numpy as jnp
        return jnp.argmax(self.logits, axis=-1)

    def kl(self, other: "Categorical"):
        import jax
        import jax.numpy as jnp
        p = jax.nn.softmax(self.logits, axis=-1)
        return jnp.sum(
            p * (jax.nn.log_softmax(self.logits, axis=-1)
                 - jax.nn.log_softmax(other.logits, axis=-1)), axis=-1)
