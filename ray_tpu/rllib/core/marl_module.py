"""MultiAgentRLModule: independently-parameterized policies in one tree.

reference parity: rllib/core/rl_module/marl_module.py:40
(MultiAgentRLModule — a container of RLModules keyed by module_id,
routed by AlgorithmConfig.policy_mapping_fn) and
rllib/policy/sample_batch.py MultiAgentBatch (per-module sub-batches).

TPU-first shape: the multi-agent params are ONE pytree
{module_id: module_params}, so a single jitted update computes every
module's loss, sums them (independent gradients — the per-module losses
the reference computes module-by-module), and applies one optimizer over
the union tree. Per-module sub-batches have static shapes because the
lane→module assignment is fixed by the roster + mapping fn, so XLA
never sees data-dependent partitioning.
"""

from __future__ import annotations

from typing import Any, Dict

from ray_tpu.rllib.core.rl_module import RLModule


class MultiAgentRLModule:
    """Container of per-policy RLModules keyed by module_id."""

    def __init__(self, modules: Dict[str, RLModule]):
        if not modules:
            raise ValueError("MultiAgentRLModule needs at least one module")
        self.modules = dict(modules)

    @property
    def module_ids(self):
        return sorted(self.modules)

    def __getitem__(self, module_id: str) -> RLModule:
        return self.modules[module_id]

    def init_params(self, key) -> Dict[str, Any]:
        import jax
        keys = jax.random.split(key, len(self.modules))
        return {mid: self.modules[mid].init_params(k)
                for mid, k in zip(self.module_ids, keys)}
