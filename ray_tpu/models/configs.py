"""Transformer configurations.

Scales match the BASELINE.json north-star configs: GPT-2 125M for the
data-parallel benchmark, Llama-2 7B for the FSDP benchmark.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: Optional[int] = None      # None -> = n_heads (MHA)
    d_ff: Optional[int] = None            # None -> 4*d_model (8/3 for swiglu
                                          # users should set explicitly)
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    # "auto" | "dense" | "flash" | "ring" | "ulysses". auto = pallas
    # flash kernel on TPU when the seq axis is unsharded (ring when it
    # is), dense elsewhere; dense = materialized-scores attention with
    # GSPMD-managed layout; ring/ulysses = explicit shard_map SP.
    attention_impl: str = "dense"
    # dtypes: params kept in param_dtype, compute runs in dtype (bf16 on
    # TPU keeps the MXU fed; accumulation is f32 via preferred_element_type)
    dtype: Any = "bfloat16"
    param_dtype: Any = "float32"
    remat: bool = False                   # jax.checkpoint each layer
    # "full": recompute the whole layer in bwd (min memory, +1 fwd pass);
    # "dots": save matmul outputs, recompute only elementwise chains
    # (near-zero recompute FLOPs — fastest when activations fit; pick it
    # explicitly for small/mid models like the GPT-2 bench config).
    remat_policy: str = "full"
    # chunk the lm-head + cross-entropy over the sequence axis so the
    # [B,T,vocab] f32 logits (+grad) never materialize at once; 0 = off.
    loss_chunk: int = 256
    # unroll factor for the layer scan. True unrolls fully: XLA sees
    # static weight slices (no dynamic-slice bookkeeping per layer) and
    # can fuse across layer boundaries; costs compile time, wins step
    # time for shallow stacks. Keep 1 (rolled) for deep models and for
    # the pipeline axis.
    scan_unroll: int = 1
    # Mixture-of-Experts FFN (ops/moe.py Switch-style router): 0 = dense
    # FFN; >0 replaces every layer's FFN with moe_experts experts whose
    # weights shard over the "expert" mesh axis. The router aux
    # (load-balancing) loss is added to the LM loss with moe_aux_coeff.
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_coeff: float = 0.01

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def ff_dim(self) -> int:
        return self.d_ff or 4 * self.d_model

    def replace(self, **kw) -> "TransformerConfig":
        return dataclasses.replace(self, **kw)

    @property
    def num_params(self) -> int:
        d, l, f, v = self.d_model, self.n_layers, self.ff_dim, self.vocab_size
        hd, nh, nkv = self.head_dim, self.n_heads, self.kv_heads
        attn = d * nh * hd + 2 * d * nkv * hd + nh * hd * d
        mlp = 3 * d * f
        norms = 2 * d
        head = 0 if self.tie_embeddings else d * v
        return v * d + l * (attn + mlp + norms) + d + head


TINY = TransformerConfig(
    vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=128)

# GPT-2 small scale (125M): 12L/768d, 50k vocab, learned-pos in the
# original — here RoPE (TPU-first redesign, not a port). Head shape is
# 6 heads x 128 head_dim rather than the original 12 x 64: identical
# parameter count and FLOPs (d_total = 768 either way), but head_dim
# 128 fills the MXU's 128-lane contraction on the QK^T/PV matmuls where
# 64 leaves half the array idle, and 6 heads halve the softmax VPU work
# — measured +30% train-step throughput on v5e-class chips.
GPT2_125M = TransformerConfig(
    vocab_size=50304,  # 50257 padded to a multiple of 128 for the MXU
    d_model=768, n_layers=12, n_heads=6, d_ff=3072, max_seq_len=1024,
    tie_embeddings=True)

LLAMA2_7B = TransformerConfig(
    vocab_size=32000, d_model=4096, n_layers=32, n_heads=32,
    n_kv_heads=32, d_ff=11008, max_seq_len=4096, norm_eps=1e-5,
    remat=True)
