"""ray_tpu.models: TPU-first model zoo.

The reference ships no in-tree language models (its models live in RLlib's
policy nets, reference: python/ray/rllib/models/ — torch/tf MLP+CNN
catalogs); LLM training flows through user-supplied torch modules (e.g.
the DeepSpeed 7B fine-tune example,
reference: train/examples/deepspeed/deepspeed_torch_trainer.py). The TPU
rebuild makes the flagship model family first-class: a decoder-only
transformer (Llama-style: RMSNorm/RoPE/SwiGLU/GQA, covering GPT-2-125M
through Llama-2-7B scales per BASELINE.json configs), written as pure
pytrees + jax functions with logical sharding specs so one definition runs
dense, FSDP, TP, sequence-parallel (ring/Ulysses) and their combinations.
"""

from ray_tpu.models.configs import (GPT2_125M, LLAMA2_7B, TINY,  # noqa: F401
                                    TransformerConfig)
from ray_tpu.models.transformer import Transformer  # noqa: F401

__all__ = [
    "TransformerConfig", "Transformer", "TINY", "GPT2_125M", "LLAMA2_7B",
]
