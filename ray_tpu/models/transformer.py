"""Decoder-only transformer, TPU-first.

Pure pytree params + jax functions (no framework objects cross the jit
boundary). One definition covers every parallelism mode: params carry
logical axis names (ray_tpu.parallel.sharding) so the same apply() runs
replicated, FSDP ("embed"->fsdp), tensor-parallel ("heads"/"mlp"->tensor),
and sequence-parallel (ring/Ulysses attention over the "seq" axis) — XLA
inserts the collectives. Layers are stacked and iterated with `lax.scan`
(one compiled layer body regardless of depth — fast compiles, and the
stacked leading dim is the natural pipeline-parallel axis).

Reference parity note: the reference has no in-tree LM (SURVEY.md §2.3,
§5.7); its model math arrives via user torch code over NCCL groups. This
module is the TPU-native replacement for that entire delegated stack.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu.models.configs import TransformerConfig
from ray_tpu.parallel.mesh import AXIS_SEQ
from ray_tpu.parallel.sharding import ShardingRules, with_logical_constraint


def _rope(x, positions, theta):
    """Rotary position embedding on [..., T, H, D] with explicit positions
    (global positions keep RoPE exact when the sequence axis is sharded)."""
    import jax.numpy as jnp

    d = x.shape[-1]
    half = d // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [..., T, half]
    cos = jnp.cos(angles)[..., None, :]  # broadcast over heads: [...,T,1,half]
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _rmsnorm(x, w, eps):
    import jax.numpy as jnp
    x32 = x.astype(jnp.float32)
    scale = jnp.reciprocal(
        jnp.sqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps))
    return (x32 * scale).astype(x.dtype) * w.astype(x.dtype)


class Transformer:
    """Namespace for init / param_specs / apply / loss."""

    # ---- parameter construction ------------------------------------
    @staticmethod
    def init(key, cfg: TransformerConfig) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        pdt = jnp.dtype(cfg.param_dtype)
        d, hd = cfg.d_model, cfg.head_dim
        nh, nkv, f, l = cfg.n_heads, cfg.kv_heads, cfg.ff_dim, cfg.n_layers
        keys = jax.random.split(key, 8)

        def norm_init(stddev, k, shape):
            return (jax.random.normal(k, shape, jnp.float32)
                    * stddev).astype(pdt)

        params = {
            "embed": norm_init(0.02, keys[0], (cfg.vocab_size, d)),
            "layers": {
                "attn_norm": jnp.ones((l, d), pdt),
                "wq": norm_init(d ** -0.5, keys[1], (l, d, nh, hd)),
                "wk": norm_init(d ** -0.5, keys[2], (l, d, nkv, hd)),
                "wv": norm_init(d ** -0.5, keys[3], (l, d, nkv, hd)),
                "wo": norm_init((nh * hd) ** -0.5, keys[4], (l, nh, hd, d)),
                "mlp_norm": jnp.ones((l, d), pdt),
                "w_gate": norm_init(d ** -0.5, keys[5], (l, d, f)),
                "w_up": norm_init(d ** -0.5, keys[6], (l, d, f)),
                "w_down": norm_init(f ** -0.5, keys[7], (l, f, d)),
            },
            "final_norm": jnp.ones((d,), pdt),
        }
        if not cfg.tie_embeddings:
            params["lm_head"] = norm_init(
                d ** -0.5, jax.random.fold_in(key, 99), (d, cfg.vocab_size))
        return params

    @staticmethod
    def param_specs(cfg: TransformerConfig) -> Dict[str, Any]:
        """Logical sharding spec tree, same structure as init()'s output."""
        specs = {
            "embed": ("vocab", "embed"),
            "layers": {
                "attn_norm": ("layers", "norm"),
                "wq": ("layers", "embed", "heads", "head_dim"),
                "wk": ("layers", "embed", "kv_heads", "head_dim"),
                "wv": ("layers", "embed", "kv_heads", "head_dim"),
                "wo": ("layers", "heads", "head_dim", "embed"),
                "mlp_norm": ("layers", "norm"),
                "w_gate": ("layers", "embed", "mlp"),
                "w_up": ("layers", "embed", "mlp"),
                "w_down": ("layers", "mlp", "embed"),
            },
            "final_norm": ("norm",),
        }
        if not cfg.tie_embeddings:
            specs["lm_head"] = ("embed", "vocab")
        return specs

    # ---- forward ----------------------------------------------------
    @staticmethod
    def apply(params, tokens, cfg: TransformerConfig, *,
              mesh=None, rules: Optional[ShardingRules] = None,
              positions=None):
        """tokens [B, T] int32 -> logits [B, T, vocab] (compute dtype).

        When `mesh` is provided and cfg.attention_impl is ring/ulysses, the
        attention op runs inside shard_map over the "seq" axis; everything
        else is GSPMD via logical sharding constraints.
        """
        import jax
        import jax.numpy as jnp
        from jax import lax

        rules = rules or ShardingRules()
        cdt = jnp.dtype(cfg.dtype)
        b, t = tokens.shape
        if positions is None:
            positions = jnp.arange(t, dtype=jnp.int32)[None, :]

        constrain = functools.partial(
            with_logical_constraint, mesh=mesh, rules=rules)

        x = jnp.take(params["embed"], tokens, axis=0).astype(cdt)
        x = constrain(x, ("batch", "seq", "act_embed"))

        attn_fn = Transformer._make_attention(cfg, mesh, rules)
        scale = cfg.head_dim ** -0.5

        def layer(x, lp):
            h = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
            q = jnp.einsum("btd,dhk->bthk", h, lp["wq"].astype(cdt))
            k = jnp.einsum("btd,dhk->bthk", h, lp["wk"].astype(cdt))
            v = jnp.einsum("btd,dhk->bthk", h, lp["wv"].astype(cdt))
            q = _rope(q, positions, cfg.rope_theta)
            k = _rope(k, positions, cfg.rope_theta)
            if cfg.kv_heads != cfg.n_heads:
                rep = cfg.n_heads // cfg.kv_heads
                k = jnp.repeat(k, rep, axis=2)
                v = jnp.repeat(v, rep, axis=2)
            q = constrain(q, ("batch", "seq", "heads", "head_dim"))
            k = constrain(k, ("batch", "seq", "heads", "head_dim"))
            v = constrain(v, ("batch", "seq", "heads", "head_dim"))
            o = attn_fn(q, k, v, scale)
            o = constrain(o, ("batch", "seq", "heads", "head_dim"))
            o = jnp.einsum("bthk,hkd->btd", o, lp["wo"].astype(cdt))
            x = x + constrain(o, ("batch", "seq", "act_embed"))

            h = _rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
            gate = jnp.einsum("btd,df->btf", h, lp["w_gate"].astype(cdt))
            up = jnp.einsum("btd,df->btf", h, lp["w_up"].astype(cdt))
            ff = jax.nn.silu(gate) * up
            ff = constrain(ff, ("batch", "seq", "act_mlp"))
            down = jnp.einsum("btf,fd->btd", ff, lp["w_down"].astype(cdt))
            x = x + constrain(down, ("batch", "seq", "act_embed"))
            return x

        if cfg.remat:
            layer = jax.checkpoint(layer)

        def scan_body(x, lp):
            return layer(x, lp), None

        x, _ = lax.scan(scan_body, x, params["layers"])

        x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
        head = (params["embed"].T if cfg.tie_embeddings
                else params["lm_head"])
        logits = jnp.einsum("btd,dv->btv", x, head.astype(cdt),
                            preferred_element_type=jnp.float32)
        return constrain(logits, ("batch", "seq", "act_vocab"))

    @staticmethod
    def _make_attention(cfg: TransformerConfig, mesh, rules: ShardingRules):
        import jax
        from jax.sharding import PartitionSpec as P

        from ray_tpu.ops.attention import dense_attention

        impl = cfg.attention_impl
        if impl not in ("dense", "ring", "ulysses"):
            raise ValueError(f"unknown attention_impl {impl!r}")
        if impl == "dense" or mesh is None or mesh.shape.get(AXIS_SEQ, 1) == 1:
            return lambda q, k, v, scale: dense_attention(
                q, k, v, causal=True, scale=scale)

        from ray_tpu.parallel.ring import ring_attention
        from ray_tpu.parallel.ulysses import ulysses_attention

        # Heads stay sharded over the tensor axis inside the shard_map —
        # SP composes with TP instead of all-gathering Q/K/V heads.
        batch_axes = rules.mesh_axes("batch")
        heads_axes = rules.mesh_axes("heads")
        qkv_spec = P(batch_axes, AXIS_SEQ, heads_axes, None)

        if impl == "ring":
            body = lambda q, k, v, scale: ring_attention(  # noqa: E731
                q, k, v, causal=True, scale=scale)
        else:
            body = lambda q, k, v, scale: ulysses_attention(  # noqa: E731
                q, k, v, causal=True, scale=scale)

        def sharded(q, k, v, scale):
            fn = jax.shard_map(
                functools.partial(body, scale=scale), mesh=mesh,
                in_specs=(qkv_spec, qkv_spec, qkv_spec),
                out_specs=qkv_spec)
            return fn(q, k, v)

        return sharded

    # ---- loss -------------------------------------------------------
    @staticmethod
    def loss(params, batch, cfg: TransformerConfig, *,
             mesh=None, rules: Optional[ShardingRules] = None):
        """Next-token cross-entropy. batch = {"tokens": [B,T+1] or
        ("tokens","targets") pair}; returns scalar mean loss (f32)."""
        import jax.numpy as jnp

        if "targets" in batch:
            tokens, targets = batch["tokens"], batch["targets"]
        else:
            tokens, targets = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
        logits = Transformer.apply(params, tokens, cfg, mesh=mesh,
                                   rules=rules)
        import jax
        logits = logits.astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(
            logits, targets[..., None], axis=-1)[..., 0]
        mask = batch.get("mask")
        nll = logz - gold
        if mask is not None:
            return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        return jnp.mean(nll)
