"""Decoder-only transformer, TPU-first.

Pure pytree params + jax functions (no framework objects cross the jit
boundary). One definition covers every parallelism mode: params carry
logical axis names (ray_tpu.parallel.sharding) so the same apply() runs
replicated, FSDP ("embed"->fsdp), tensor-parallel ("heads"/"mlp"->tensor),
and sequence-parallel (ring/Ulysses attention over the "seq" axis) — XLA
inserts the collectives. Layers are stacked and iterated with `lax.scan`
(one compiled layer body regardless of depth — fast compiles, and the
stacked leading dim is the natural pipeline-parallel axis).

Reference parity note: the reference has no in-tree LM (SURVEY.md §2.3,
§5.7); its model math arrives via user torch code over NCCL groups. This
module is the TPU-native replacement for that entire delegated stack.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu.models.configs import TransformerConfig
from ray_tpu.parallel.mesh import AXIS_SEQ
from ray_tpu.parallel.sharding import ShardingRules, with_logical_constraint


def _rope_tables(positions, head_dim, theta):
    """cos/sin tables [..., T, half] (f32) for explicit positions — global
    positions keep RoPE exact when the sequence axis is sharded. Computed
    once per forward and closed over by the layer scan (not recomputed
    per layer)."""
    import jax.numpy as jnp

    half = head_dim // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs
    return jnp.cos(angles), jnp.sin(angles)


def _rope(x, cos, sin):
    """Apply rotary embedding to [..., T, H, D] given [..., T, half]
    tables."""
    import jax.numpy as jnp

    half = x.shape[-1] // 2
    c = cos[..., None, :]  # broadcast over heads: [..., T, 1, half]
    s = sin[..., None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
    return out.astype(x.dtype)


def _rmsnorm(x, w, eps):
    import jax.numpy as jnp
    x32 = x.astype(jnp.float32)
    scale = jnp.reciprocal(
        jnp.sqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps))
    return (x32 * scale).astype(x.dtype) * w.astype(x.dtype)


class Transformer:
    """Namespace for init / param_specs / apply / loss."""

    # ---- parameter construction ------------------------------------
    @staticmethod
    def init(key, cfg: TransformerConfig) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        pdt = jnp.dtype(cfg.param_dtype)
        d, hd = cfg.d_model, cfg.head_dim
        nh, nkv, f, l = cfg.n_heads, cfg.kv_heads, cfg.ff_dim, cfg.n_layers
        keys = jax.random.split(key, 8)

        def norm_init(stddev, k, shape):
            return (jax.random.normal(k, shape, jnp.float32)
                    * stddev).astype(pdt)

        # QKV and gate/up projections are FUSED along an unsharded group
        # axis (one wide MXU matmul instead of 3/2 narrow ones; slicing the
        # group axis never crosses a shard boundary). MHA fuses q,k,v into
        # wqkv[..., 3, nh, hd]; GQA keeps wq separate and fuses k,v.
        layers = {
            "attn_norm": jnp.ones((l, d), pdt),
            "wo": norm_init((nh * hd) ** -0.5, keys[4], (l, nh, hd, d)),
            "mlp_norm": jnp.ones((l, d), pdt),
        }
        if cfg.moe_experts:
            # routed expert FFN (ops/moe.py): per-layer router + stacked
            # expert weights, expert dim sharded over the "expert" axis
            e = cfg.moe_experts
            layers["w_router"] = norm_init(
                0.02, keys[5], (l, d, e)).astype(jnp.float32)
            layers["w_moe_up"] = norm_init(
                d ** -0.5, keys[6], (l, e, d, f))
            layers["w_moe_down"] = norm_init(
                f ** -0.5, keys[7], (l, e, f, d))
        else:
            layers["w_gateup"] = jnp.stack(
                [norm_init(d ** -0.5, keys[5], (l, d, f)),
                 norm_init(d ** -0.5, keys[6], (l, d, f))],
                axis=2)  # (l, d, 2, f)
            layers["w_down"] = norm_init(f ** -0.5, keys[7], (l, f, d))
        if nkv == nh:
            layers["wqkv"] = jnp.stack(
                [norm_init(d ** -0.5, keys[1], (l, d, nh, hd)),
                 norm_init(d ** -0.5, keys[2], (l, d, nh, hd)),
                 norm_init(d ** -0.5, keys[3], (l, d, nh, hd))],
                axis=2)  # (l, d, 3, nh, hd)
        else:
            layers["wq"] = norm_init(d ** -0.5, keys[1], (l, d, nh, hd))
            layers["wkv"] = jnp.stack(
                [norm_init(d ** -0.5, keys[2], (l, d, nkv, hd)),
                 norm_init(d ** -0.5, keys[3], (l, d, nkv, hd))],
                axis=2)  # (l, d, 2, nkv, hd)
        params = {
            "embed": norm_init(0.02, keys[0], (cfg.vocab_size, d)),
            "layers": layers,
            "final_norm": jnp.ones((d,), pdt),
        }
        if not cfg.tie_embeddings:
            params["lm_head"] = norm_init(
                d ** -0.5, jax.random.fold_in(key, 99), (d, cfg.vocab_size))
        return params

    @staticmethod
    def param_specs(cfg: TransformerConfig) -> Dict[str, Any]:
        """Logical sharding spec tree, same structure as init()'s output."""
        layers = {
            "attn_norm": ("layers", "norm"),
            "wo": ("layers", "heads", "head_dim", "embed"),
            "mlp_norm": ("layers", "norm"),
        }
        if cfg.moe_experts:
            layers["w_router"] = ("layers", "embed", None)
            layers["w_moe_up"] = ("layers", "expert", "embed", "mlp")
            layers["w_moe_down"] = ("layers", "expert", "mlp", "embed")
        else:
            layers["w_gateup"] = ("layers", "embed", None, "mlp")
            layers["w_down"] = ("layers", "mlp", "embed")
        if cfg.kv_heads == cfg.n_heads:
            layers["wqkv"] = ("layers", "embed", None, "heads", "head_dim")
        else:
            layers["wq"] = ("layers", "embed", "heads", "head_dim")
            layers["wkv"] = ("layers", "embed", None, "kv_heads",
                             "head_dim")
        specs = {
            "embed": ("vocab", "embed"),
            "layers": layers,
            "final_norm": ("norm",),
        }
        if not cfg.tie_embeddings:
            specs["lm_head"] = ("embed", "vocab")
        return specs

    # ---- forward ----------------------------------------------------
    @staticmethod
    def hidden(params, tokens, cfg: TransformerConfig, *,
               mesh=None, rules: Optional[ShardingRules] = None,
               positions=None, with_aux: bool = False):
        """tokens [B, T] int32 -> final-norm hidden states [B, T, d]
        (compute dtype) — apply() stopping before the lm head, so the
        loss can chunk head+softmax over T (the f32 [B,T,vocab] logits
        and their grad are the biggest HBM tenant at GPT-2 scale).
        with_aux=True returns (hidden, aux_loss) where aux_loss is the
        summed MoE load-balancing loss (0 for dense FFN configs).

        When `mesh` is provided and cfg.attention_impl is ring/ulysses, the
        attention op runs inside shard_map over the "seq" axis; everything
        else is GSPMD via logical sharding constraints.
        """
        import jax
        import jax.numpy as jnp
        from jax import lax

        rules = rules or ShardingRules()
        cdt = jnp.dtype(cfg.dtype)
        b, t = tokens.shape
        if positions is None:
            positions = jnp.arange(t, dtype=jnp.int32)[None, :]

        constrain = functools.partial(
            with_logical_constraint, mesh=mesh, rules=rules)

        # Constrain the lookup operand's embed dim to the ACTIVATION
        # sharding (replicated / tensor) rather than the param's fsdp
        # sharding: with the table's feature dim matching the output
        # layout, the gather partitions on the (batch/seq-sharded) index
        # dims directly. Leaving it fsdp-sharded makes SPMD emit a
        # d-sharded gather then an "involuntary full rematerialization"
        # to reshard d->batch/seq. This is the FSDP gather-at-use
        # pattern: fwd all-gathers the table's d shards, bwd
        # reduce-scatters the grad.
        emb = constrain(params["embed"], ("vocab", "act_embed"))
        x = jnp.take(emb, tokens, axis=0).astype(cdt)
        x = constrain(x, ("batch", "seq", "act_embed"))

        cos, sin = _rope_tables(positions, cfg.head_dim, cfg.rope_theta)
        layer = Transformer._make_layer_fn(cfg, mesh, rules, cos, sin)

        if cfg.remat:
            if cfg.remat_policy == "dots":
                pol = jax.checkpoint_policies.save_from_both_policies(
                    jax.checkpoint_policies.checkpoint_dots,
                    jax.checkpoint_policies.save_only_these_names(
                        "attn_out"))
                layer = jax.checkpoint(layer, policy=pol)
            else:
                layer = jax.checkpoint(layer)

        def scan_body(carry, lp):
            x, aux_tot = carry
            x, aux = layer(x, lp)
            return (x, aux_tot + aux), None

        (x, aux_total), _ = lax.scan(
            scan_body, (x, jnp.zeros((), jnp.float32)), params["layers"],
            unroll=cfg.scan_unroll)

        out = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
        if with_aux:
            return out, aux_total
        return out

    @staticmethod
    def _make_layer_fn(cfg: TransformerConfig, mesh,
                       rules: ShardingRules, cos, sin):
        """Build layer(x, lp) -> (x, moe_aux) — the per-layer body shared
        by hidden()'s scan and the pipeline stage executor
        (parallel/pipeline.py make_pipeline_fn)."""
        import jax
        import jax.numpy as jnp

        cdt = jnp.dtype(cfg.dtype)
        constrain = functools.partial(
            with_logical_constraint, mesh=mesh, rules=rules)
        attn_fn = Transformer._make_attention(cfg, mesh, rules)
        scale = cfg.head_dim ** -0.5

        def layer(x, lp):
            h = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
            if cfg.kv_heads == cfg.n_heads:
                qkv = jnp.einsum("btd,dghk->btghk", h,
                                 lp["wqkv"].astype(cdt))
                q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            else:
                q = jnp.einsum("btd,dhk->bthk", h, lp["wq"].astype(cdt))
                kv = jnp.einsum("btd,dghk->btghk", h,
                                lp["wkv"].astype(cdt))
                k, v = kv[:, :, 0], kv[:, :, 1]
            q = _rope(q, cos, sin)
            k = _rope(k, cos, sin)
            # GQA: k/v keep their true kv_heads width end-to-end — the
            # attention ops broadcast per group internally (ring then
            # rotates Hkv-wide tensors over ICI, not Hq-wide repeats)
            q = constrain(q, ("batch", "seq", "heads", "head_dim"))
            k = constrain(k, ("batch", "seq", "kv_heads", "head_dim"))
            v = constrain(v, ("batch", "seq", "kv_heads", "head_dim"))
            o = attn_fn(q, k, v, scale)
            # name the (pallas) attention output so the "dots" remat
            # policy can save it — it isn't a dot, and recomputing the
            # kernel in bwd costs a full extra attention pass
            from jax.ad_checkpoint import checkpoint_name
            o = checkpoint_name(o, "attn_out")
            o = constrain(o, ("batch", "seq", "heads", "head_dim"))
            o = jnp.einsum("bthk,hkd->btd", o, lp["wo"].astype(cdt))
            x = x + constrain(o, ("batch", "seq", "act_embed"))

            h = _rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
            if cfg.moe_experts:
                from ray_tpu.ops.moe import moe_ffn
                bsz, tsz, dsz = h.shape
                y, aux = moe_ffn(
                    {"w_router": lp["w_router"],
                     "w_up": lp["w_moe_up"].astype(cdt),
                     "w_down": lp["w_moe_down"].astype(cdt)},
                    h.reshape(bsz * tsz, dsz),
                    num_selected=cfg.moe_top_k,
                    capacity_factor=cfg.moe_capacity_factor,
                    rules=rules)
                down = y.reshape(bsz, tsz, dsz).astype(cdt)
                x = x + constrain(down, ("batch", "seq", "act_embed"))
                return x, aux
            gu = jnp.einsum("btd,dgf->btgf", h, lp["w_gateup"].astype(cdt))
            ff = jax.nn.silu(gu[:, :, 0]) * gu[:, :, 1]
            ff = constrain(ff, ("batch", "seq", "act_mlp"))
            down = jnp.einsum("btf,fd->btd", ff, lp["w_down"].astype(cdt))
            x = x + constrain(down, ("batch", "seq", "act_embed"))
            return x, jnp.zeros((), jnp.float32)

        return layer

    @staticmethod
    def _head_logits(params, x, cfg: TransformerConfig, *,
                     mesh=None, rules: Optional[ShardingRules] = None):
        """hidden states [B, T, d] -> f32 logits [B, T, vocab] — the one
        lm-head projection shared by apply() and loss()."""
        import jax.numpy as jnp

        head = (params["embed"].T if cfg.tie_embeddings
                else params["lm_head"])
        logits = jnp.einsum("btd,dv->btv", x, head.astype(x.dtype),
                            preferred_element_type=jnp.float32)
        return with_logical_constraint(
            logits, ("batch", "seq", "act_vocab"), mesh=mesh, rules=rules)

    @staticmethod
    def apply(params, tokens, cfg: TransformerConfig, *,
              mesh=None, rules: Optional[ShardingRules] = None,
              positions=None):
        """tokens [B, T] int32 -> logits [B, T, vocab] (f32 accum)."""
        rules = rules or ShardingRules()
        x = Transformer.hidden(params, tokens, cfg, mesh=mesh, rules=rules,
                               positions=positions)
        return Transformer._head_logits(params, x, cfg, mesh=mesh,
                                        rules=rules)

    @staticmethod
    def pipeline_loss(params, batch, cfg: TransformerConfig, *,
                      mesh, n_stages: int, n_micro: int,
                      rules: Optional[ShardingRules] = None):
        """Next-token loss with the layer stack executed as a microbatched
        ppermute pipeline over the "pipe" mesh axis
        (parallel/pipeline.py make_pipeline_fn) — the alternative
        execution of the same stacked layer params hidden() scans.

        Embedding runs outside the pipeline (vocab/fsdp-sharded GSPMD);
        each stage applies n_layers/n_stages layers; the last stage's
        loss_fn does final-norm + lm-head + CE per microbatch. Requires
        batch divisible by n_micro, n_layers divisible by n_stages, and a
        stage-local attention impl (dense/flash — seq stays unsharded
        inside a stage)."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        from ray_tpu.parallel.pipeline import make_pipeline_fn

        rules = rules or ShardingRules()
        cdt = jnp.dtype(cfg.dtype)
        if "targets" in batch:
            tokens, targets = batch["tokens"], batch["targets"]
        else:
            tokens, targets = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
        b, t = tokens.shape
        if b % n_micro or cfg.n_layers % n_stages:
            raise ValueError(
                f"batch {b} % n_micro {n_micro} or n_layers "
                f"{cfg.n_layers} % n_stages {n_stages} != 0")
        if cfg.attention_impl in ("ring", "ulysses"):
            raise ValueError("pipeline stages need stage-local attention "
                             "(dense/flash), not ring/ulysses")
        if cfg.moe_experts:
            raise ValueError(
                "pipeline_loss does not thread the MoE aux "
                "(load-balancing) loss out of the pipeline yet; train "
                "MoE configs via Transformer.loss (expert axis), or set "
                "moe_experts=0 for the pipe axis")
        mb = b // n_micro

        # Embed outside the pipeline, then split into microbatches.
        emb = with_logical_constraint(
            params["embed"], ("vocab", "act_embed"), mesh=mesh, rules=rules)
        x = jnp.take(emb, tokens, axis=0).astype(cdt)   # [B, T, d]
        x_micro = x.reshape(n_micro, mb, t, x.shape[-1])
        y_micro = targets.reshape(n_micro, mb, t)

        per_stage = cfg.n_layers // n_stages

        def stage_fn(stage_params, x):
            # rope tables rebuilt from static positions inside the stage:
            # shard-local constants, not closure-captured traced arrays
            # (shard_map rejects auto-sharded implicit captures)
            positions = jnp.arange(t, dtype=jnp.int32)[None, :]
            cos, sin = _rope_tables(positions, cfg.head_dim,
                                    cfg.rope_theta)
            # mesh=None inside the stage: the pipeline shard_map already
            # owns axis mapping; constraints no-op under manual meshes.
            layer = Transformer._make_layer_fn(cfg, None, rules, cos, sin)
            if cfg.remat:
                # same per-layer rematerialization contract as hidden()
                if cfg.remat_policy == "dots":
                    pol = jax.checkpoint_policies.save_from_both_policies(
                        jax.checkpoint_policies.checkpoint_dots,
                        jax.checkpoint_policies.save_only_these_names(
                            "attn_out"))
                    layer = jax.checkpoint(layer, policy=pol)
                else:
                    layer = jax.checkpoint(layer)

            def body(x, lp):
                x, _aux = layer(x, lp)
                return x, None
            x, _ = lax.scan(body, x, stage_params)
            return x

        def mb_loss(out, y, extras):
            h = _rmsnorm(out, extras["final_norm"], cfg.norm_eps)
            logits = jnp.einsum("btd,dv->btv", h,
                                extras["head"].astype(h.dtype),
                                preferred_element_type=jnp.float32)
            logz = jax.nn.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(
                logits, y[..., None], axis=-1)[..., 0]
            return jnp.mean(logz - gold)

        run = make_pipeline_fn(stage_fn, n_stages, n_micro, mesh,
                               loss_fn=mb_loss)
        # [l, ...] stacked layers -> [n_stages, l/n_stages, ...]: the
        # leading stage dim aligns with the "pipe" shards of the "layers"
        # axis, so this reshape is shard-local.
        staged = jax.tree.map(
            lambda a: a.reshape((n_stages, per_stage) + a.shape[1:]),
            params["layers"])
        extras = {
            "final_norm": params["final_norm"],
            "head": (params["embed"].T if cfg.tie_embeddings
                     else params["lm_head"]),
        }
        return run(staged, x_micro, y_micro, extras)

    @staticmethod
    def _make_attention(cfg: TransformerConfig, mesh, rules: ShardingRules):
        import jax
        from jax.sharding import PartitionSpec as P

        from ray_tpu.ops.attention import dense_attention, flash_attention

        impl = cfg.attention_impl
        if impl not in ("auto", "dense", "flash", "ring", "ulysses"):
            raise ValueError(f"unknown attention_impl {impl!r}")
        seq_unsharded = mesh is None or mesh.shape.get(AXIS_SEQ, 1) == 1
        if impl == "auto":
            impl = "flash" if seq_unsharded else "ring"
        if impl == "flash" and not seq_unsharded:
            raise ValueError("attention_impl='flash' requires an unsharded "
                             "seq axis; use ring/ulysses for SP")
        # [B, T, H, D] specs shared by every shard_map path; only the seq
        # entry differs (sharded for ring/ulysses SP, local for flash).
        # k/v get their own spec so GQA kv heads shard by the kv_heads
        # rule without being repeated to query-head width — UNLESS the
        # backing mesh axis doesn't divide kv_heads (TP degree > kv
        # heads), in which case k/v are widened to query heads first
        # (the pre-round-4 behavior) so shard_map can still split them.
        from ray_tpu.parallel.sharding import spec_entry_size

        def axis_size(logical):
            return spec_entry_size(rules.mesh_axes(logical), mesh) \
                if mesh is not None else 1

        kv_narrow = (mesh is not None and cfg.kv_heads != cfg.n_heads
                     and cfg.kv_heads % axis_size("kv_heads") == 0)
        kv_axis = "kv_heads" if (kv_narrow or cfg.kv_heads == cfg.n_heads) \
            else "heads"

        def maybe_widen(fn):
            if kv_axis == "kv_heads":
                return fn
            import jax.numpy as jnp
            rep = cfg.n_heads // cfg.kv_heads

            def widened(q, k, v, scale):
                return fn(q, jnp.repeat(k, rep, axis=2),
                          jnp.repeat(v, rep, axis=2), scale)
            return widened

        def qkv_spec(seq_entry, head_axis="heads"):
            return P(rules.mesh_axes("batch"), seq_entry,
                     rules.mesh_axes(head_axis), None)

        def shard_mapped(body, spec, kv_spec, **shard_map_kw):
            def wrapped(q, k, v, scale):
                fn = jax.shard_map(
                    functools.partial(body, scale=scale), mesh=mesh,
                    in_specs=(spec, kv_spec, kv_spec), out_specs=spec,
                    **shard_map_kw)
                return fn(q, k, v)
            return maybe_widen(wrapped)

        if impl in ("dense", "flash") or seq_unsharded:
            local = flash_attention if impl == "flash" else dense_attention
            body = functools.partial(local, causal=True)
            if impl == "flash" and mesh is not None:
                # pallas kernels don't GSPMD-partition; run per-shard under
                # shard_map with batch/heads sharded as the constraints say.
                return shard_mapped(body, qkv_spec(None),
                                    qkv_spec(None, kv_axis),
                                    check_vma=False)
            return lambda q, k, v, scale: body(q, k, v, scale=scale)

        from ray_tpu.parallel.ring import ring_attention
        from ray_tpu.parallel.ulysses import ulysses_attention

        # Heads stay sharded over the tensor axis inside the shard_map —
        # SP composes with TP instead of all-gathering Q/K/V heads.
        sp = ring_attention if impl == "ring" else ulysses_attention
        return shard_mapped(functools.partial(sp, causal=True),
                            qkv_spec(AXIS_SEQ),
                            qkv_spec(AXIS_SEQ, kv_axis))

    # ---- loss -------------------------------------------------------
    @staticmethod
    def loss(params, batch, cfg: TransformerConfig, *,
             mesh=None, rules: Optional[ShardingRules] = None):
        """Next-token cross-entropy. batch = {"tokens": [B,T+1] or
        ("tokens","targets") pair}; returns scalar mean loss (f32)."""
        import jax.numpy as jnp

        if "targets" in batch:
            tokens, targets = batch["tokens"], batch["targets"]
        else:
            tokens, targets = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
        import jax
        from jax import lax

        mask = batch.get("mask")
        b, t = tokens.shape
        chunk = cfg.loss_chunk
        if not (chunk and t > chunk and t % chunk == 0):
            rules = rules or ShardingRules()
            x, aux = Transformer.hidden(params, tokens, cfg, mesh=mesh,
                                        rules=rules, with_aux=True)
            logits = Transformer._head_logits(params, x, cfg, mesh=mesh,
                                              rules=rules)
            logits = logits.astype(jnp.float32)
            logz = jax.nn.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(
                logits, targets[..., None], axis=-1)[..., 0]
            nll = logz - gold
            aux_term = cfg.moe_aux_coeff * aux if cfg.moe_experts else 0.0
            if mask is not None:
                return jnp.sum(nll * mask) / jnp.maximum(
                    jnp.sum(mask), 1.0) + aux_term
            return jnp.mean(nll) + aux_term

        # Chunked head + cross-entropy: scan T in loss_chunk slices so only
        # one [B, chunk, vocab] f32 logits block (and its grad, via
        # jax.checkpoint recompute) lives in HBM at a time.
        rules = rules or ShardingRules()
        x, aux = Transformer.hidden(params, tokens, cfg, mesh=mesh,
                                    rules=rules, with_aux=True)
        cdt = x.dtype
        # contract against embed directly ("vd" orientation) rather than
        # materializing a [d, vocab] transpose each step
        tied = cfg.tie_embeddings
        head = (params["embed"] if tied else params["lm_head"]).astype(cdt)
        eq = "bcd,vd->bcv" if tied else "bcd,dv->bcv"
        n = t // chunk

        def chunk_nll(x_c, t_c):
            logits = jnp.einsum(eq, x_c, head,
                                preferred_element_type=jnp.float32)
            logits = with_logical_constraint(
                logits, ("batch", None, "act_vocab"), mesh=mesh,
                rules=rules)
            logz = jax.nn.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(
                logits, t_c[..., None], axis=-1)[..., 0]
            return logz - gold  # [b, chunk] f32

        chunk_nll = jax.checkpoint(chunk_nll)
        xs = jnp.swapaxes(x.reshape(b, n, chunk, x.shape[-1]), 0, 1)
        ts = jnp.swapaxes(targets.reshape(b, n, chunk), 0, 1)
        if mask is None:
            def body(tot, xt):
                return tot + jnp.sum(chunk_nll(*xt)), None
            total, _ = lax.scan(body, jnp.zeros((), jnp.float32), (xs, ts),
                                unroll=cfg.scan_unroll > 1)
            loss_val = total / (b * t)
            if cfg.moe_experts:
                loss_val = loss_val + cfg.moe_aux_coeff * aux
            return loss_val
        ms = jnp.swapaxes(
            mask.reshape(b, n, chunk), 0, 1).astype(jnp.float32)

        def body_m(tot, xtm):
            x_c, t_c, m_c = xtm
            return tot + jnp.sum(chunk_nll(x_c, t_c) * m_c), None
        total, _ = lax.scan(body_m, jnp.zeros((), jnp.float32),
                            (xs, ts, ms), unroll=cfg.scan_unroll > 1)
        loss_val = total / jnp.maximum(jnp.sum(mask), 1.0)
        if cfg.moe_experts:
            loss_val = loss_val + cfg.moe_aux_coeff * aux
        return loss_val
