"""Push-based shuffle: tree-merged all-to-all exchange.

reference parity: python/ray/data/_internal/push_based_shuffle.py — the
reference's large-scale shuffle pipelines map tasks with intermediate
MERGE tasks: map outputs are pushed into per-partition partial merges
round by round, so (a) reducer inputs are a handful of merged partials
instead of one piece per map task (O(maps) -> O(maps/merge_factor)
refs per reducer), and (b) partial merges for round k run while round
k+1's map tasks execute — map and merge overlap instead of a full
barrier between stages.
"""

from __future__ import annotations

from typing import Any, Callable, List

import ray_tpu
from ray_tpu.data import block as block_mod


def _concat_pieces(refs: List[Any]):
    """Partial merge: concat this round's pieces for one partition."""
    blocks = [b for b in ray_tpu.get(list(refs))
              if block_mod.block_num_rows(b)]
    return block_mod.concat_blocks(blocks)


_concat_remote = None


def push_based_shuffle(input_refs: List[Any], num_partitions: int,
                       map_remote: Callable,
                       map_args: tuple = (),
                       *, merge_factor: int = 4) -> List[List[Any]]:
    """Run `map_remote(ref, *map_args)` (num_returns=num_partitions)
    over every input block, tree-merging each partition's pieces in
    rounds of `merge_factor`. Returns, per partition, the list of
    merged-partial refs for the final reduce.

    The driver only ever tracks refs; each round's pieces become one
    partial per partition as soon as that round's maps finish, while
    the next round's maps are already running.
    """
    global _concat_remote
    if _concat_remote is None:
        _concat_remote = ray_tpu.remote(_concat_pieces)
    partials: List[List[Any]] = [[] for _ in range(num_partitions)]
    n = len(input_refs)
    for lo in range(0, n, max(1, merge_factor)):
        group = input_refs[lo:lo + merge_factor]
        pieces = [map_remote.remote(r, *map_args) for r in group]
        if num_partitions == 1:
            pieces = [[p] for p in pieces]
        for p in range(num_partitions):
            round_refs = [pc[p] for pc in pieces]
            if len(round_refs) == 1:
                partials[p].append(round_refs[0])
            else:
                partials[p].append(_concat_remote.remote(round_refs))
    return partials
