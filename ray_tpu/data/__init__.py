"""ray_tpu.data: block-based datasets with a streaming executor.

reference parity: python/ray/data — Dataset over blocks, lazy transforms,
pull-based streaming execution with backpressure, per-worker train shards.
"""

from ray_tpu.data.block import Block  # noqa: F401
from ray_tpu.data.dataset import (Dataset, MaterializedDataset,  # noqa: F401
                                  from_blocks, from_items, from_numpy, range)
from ray_tpu.data.grouped import GroupedData  # noqa: F401
from ray_tpu.data.io import (from_pandas, read_csv,  # noqa: F401
                             read_json, read_parquet)
from ray_tpu.data.iterator import DataIterator  # noqa: F401

__all__ = [
    "Block", "Dataset", "MaterializedDataset", "DataIterator",
    "GroupedData", "from_items", "from_numpy", "from_blocks",
    "from_pandas", "range", "read_csv", "read_json", "read_parquet",
]

from ray_tpu._private.usage import record_library_usage as _rlu
_rlu('data')
del _rlu
