"""Block format + accessors for ray_tpu.data.

reference parity: python/ray/data/block.py (Block/BlockAccessor). The
reference's blocks are Arrow tables or pandas frames; here a block is a
columnar dict {column: np.ndarray} — the natural zero-copy format for the
shared-memory object store and for feeding jax (device_put of a dict of
arrays is one hop).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Sequence

import numpy as np

Block = Dict[str, np.ndarray]


def rows_to_block(rows: Sequence[Any]) -> Block:
    """List of dicts (or scalars → column 'item') → columnar block."""
    if not rows:
        return {}
    if not isinstance(rows[0], dict):
        rows = [{"item": r} for r in rows]
    cols: Dict[str, List[Any]] = {}
    for r in rows:
        for k, v in r.items():
            cols.setdefault(k, []).append(v)
    out: Block = {}
    for k, vals in cols.items():
        arr = np.asarray(vals)
        out[k] = arr
    return out


def block_num_rows(block: Block) -> int:
    for v in block.values():
        return len(v)
    return 0


def block_to_rows(block: Block) -> Iterator[Dict[str, Any]]:
    keys = list(block.keys())
    for i in range(block_num_rows(block)):
        yield {k: block[k][i] for k in keys}


def slice_block(block: Block, start: int, stop: int) -> Block:
    return {k: v[start:stop] for k, v in block.items()}


def take_rows(block: Block, indices: np.ndarray) -> Block:
    return {k: v[indices] for k, v in block.items()}


def concat_blocks(blocks: Sequence[Block]) -> Block:
    blocks = [b for b in blocks if block_num_rows(b) > 0]
    if not blocks:
        return {}
    keys = list(blocks[0].keys())
    return {k: np.concatenate([b[k] for b in blocks]) for k in keys}


def block_schema(block: Block) -> Dict[str, str]:
    return {k: str(v.dtype) for k, v in block.items()}


def block_size_bytes(block: Block) -> int:
    return sum(int(v.nbytes) for v in block.values())
