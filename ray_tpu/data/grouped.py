"""GroupedData: hash-partitioned groupby + aggregations.

reference parity: python/ray/data/grouped_data.py (Dataset.groupby ->
GroupedData.count/sum/min/max/mean/std/aggregate/map_groups) and the
hash-shuffle exchange in _internal/planner/exchange/. Execution shape is
the standard two-phase exchange: a map task per input block splits it
into one piece per output partition by key hash (each block crosses the
object store once), then a reduce task per partition merges its pieces
and aggregates locally with pandas (the reference's pandas-block path
does the same per-partition combine).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Sequence, Union

import numpy as np

import ray_tpu
from ray_tpu.data import block as block_mod
from ray_tpu.data.block import Block

_AGG_FUNCS = ("count", "sum", "min", "max", "mean", "std")


def _split_by_hash(blk: Block, key: str, n: int):
    """Map phase: one piece per hash partition; empty-block safe."""
    if not block_mod.block_num_rows(blk):
        return tuple({} for _ in range(n))
    import pandas as pd
    hashes = pd.util.hash_array(np.asarray(blk[key])) % n
    return tuple(
        block_mod.take_rows(blk, np.nonzero(hashes == p)[0])
        for p in range(n))


def _merge_pieces(refs: List[Any]) -> Block:
    pieces = [b for b in ray_tpu.get(list(refs))
              if block_mod.block_num_rows(b)]
    return block_mod.concat_blocks(pieces)


def _agg_pieces(refs: List[Any], key: str,
                spec: Dict[str, List[str]]) -> Block:
    import pandas as pd
    merged = _merge_pieces(refs)
    if not block_mod.block_num_rows(merged):
        return {}
    # only the key + aggregated columns enter pandas: other columns may
    # be multi-dimensional (jax feature arrays), which DataFrame rejects
    cols = [key, *spec.keys()]
    df = pd.DataFrame({c: merged[c] for c in dict.fromkeys(cols)})
    if spec:
        out = df.groupby(key, sort=True).agg(spec)
        out.columns = [f"{fn}({col})" for col, fn in out.columns]
        out = out.reset_index()
    else:  # count()
        out = df.groupby(key, sort=True).size().rename("count()") \
            .reset_index()
    return {c: out[c].to_numpy() for c in out.columns}


def _map_groups_pieces(refs: List[Any], key: str,
                       fn: Callable[[Block], Block]) -> Block:
    merged = _merge_pieces(refs)
    if not block_mod.block_num_rows(merged):
        return {}
    order = np.argsort(merged[key], kind="stable")
    merged = block_mod.take_rows(merged, order)
    keys = merged[key]
    change = np.nonzero(keys[1:] != keys[:-1])[0] + 1
    bounds = [0, *change.tolist(), len(keys)]
    outs = []
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        outs.append(fn(block_mod.slice_block(merged, lo, hi)))
    return block_mod.concat_blocks(outs)


class GroupedData:
    def __init__(self, dataset, key: str):
        self._ds = dataset
        self._key = key

    def _exchange(self, reduce_fn, *args) -> "Any":
        from ray_tpu.data.dataset import MaterializedDataset
        mat = self._ds.materialize()
        n = max(1, len(mat._refs))
        split = ray_tpu.remote(_split_by_hash).options(num_returns=n)
        pieces = [split.remote(r, self._key, n) for r in mat._refs]
        if n == 1:
            pieces = [[p] for p in pieces]
        reduce_remote = ray_tpu.remote(reduce_fn)
        refs = [reduce_remote.remote([pc[p] for pc in pieces],
                                     self._key, *args)
                for p in range(n)]
        return MaterializedDataset(refs)

    def aggregate(self, spec: Dict[str, Union[str, Sequence[str]]]):
        """spec: {column: agg | [aggs]} with aggs from
        count/sum/min/max/mean/std -> columns named 'agg(column)'."""
        norm: Dict[str, List[str]] = {}
        for col, fns in spec.items():
            if col == self._key:
                raise ValueError(
                    f"cannot aggregate the grouping key {col!r}; "
                    f"use count() for group sizes")
            fns = [fns] if isinstance(fns, str) else list(fns)
            for fn in fns:
                if fn not in _AGG_FUNCS:
                    raise ValueError(
                        f"unknown aggregation {fn!r}; "
                        f"supported: {_AGG_FUNCS}")
            norm[col] = fns
        return self._exchange(_agg_pieces, norm)

    agg = aggregate

    def count(self):
        return self._exchange(_agg_pieces, {})

    def sum(self, on: str):
        return self.aggregate({on: "sum"})

    def min(self, on: str):
        return self.aggregate({on: "min"})

    def max(self, on: str):
        return self.aggregate({on: "max"})

    def mean(self, on: str):
        return self.aggregate({on: "mean"})

    def std(self, on: str):
        return self.aggregate({on: "std"})

    def map_groups(self, fn: Callable[[Block], Block]):
        """Apply fn to each whole group's block (reference
        GroupedData.map_groups)."""
        return self._exchange(_map_groups_pieces, fn)
