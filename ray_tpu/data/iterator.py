"""DataIterator: re-batching iteration over blocks / block refs.

reference parity: python/ray/data/iterator.py (DataIterator.iter_batches)
— the object handed to train workers by get_dataset_shard
(train/_internal/session.py:1017); pulls blocks (prefetching one ahead)
and re-slices them into exact-size batches.
"""

from __future__ import annotations

from typing import Any, Iterator, List, Optional

import ray_tpu
from ray_tpu.data import block as block_mod
from ray_tpu.data.block import Block


class DataIterator:
    """Iterates blocks (given as refs or an iterator of blocks) as batches.

    Picklable when constructed from refs — this is what ships to train
    workers; the refs ride the object store and register as borrows.
    """

    def __init__(self, refs: Optional[List[Any]] = None,
                 blocks: Optional[Iterator[Block]] = None):
        assert (refs is None) != (blocks is None)
        self._refs = refs
        self._blocks = blocks

    def _block_iter(self) -> Iterator[Block]:
        if self._blocks is not None:
            yield from self._blocks
            return
        for ref in self._refs:
            # streaming: one block in memory at a time is the point
            # graftlint: disable=RT002
            yield ray_tpu.get(ref) if isinstance(ref, ray_tpu.ObjectRef) \
                else ref

    def iter_batches(self, *, batch_size: int = 256,
                     drop_last: bool = False) -> Iterator[Block]:
        # Track an offset into the current merged block instead of
        # re-concatenating the remainder per batch (slice_block returns
        # views, so in-block batching is copy-free; the only copies are one
        # remainder+next-block concat per input block).
        carry: Block = {}
        offset = 0
        for blk in self._block_iter():
            left = block_mod.block_num_rows(carry) - offset
            if left <= 0:
                carry, offset = blk, 0
            else:
                carry = block_mod.concat_blocks([
                    block_mod.slice_block(
                        carry, offset, block_mod.block_num_rows(carry)),
                    blk])
                offset = 0
            n = block_mod.block_num_rows(carry)
            while n - offset >= batch_size:
                yield block_mod.slice_block(carry, offset,
                                            offset + batch_size)
                offset += batch_size
        rest_rows = block_mod.block_num_rows(carry) - offset
        if rest_rows > 0 and not drop_last:
            yield block_mod.slice_block(
                carry, offset, block_mod.block_num_rows(carry))

    def iter_rows(self) -> Iterator[dict]:
        for blk in self._block_iter():
            yield from block_mod.block_to_rows(blk)

    def count(self) -> int:
        return sum(block_mod.block_num_rows(b) for b in self._block_iter())

    def materialize(self):
        """Back to a dataset (only for ref-backed iterators)."""
        from ray_tpu.data.dataset import MaterializedDataset
        assert self._refs is not None
        return MaterializedDataset(list(self._refs))

    def __reduce__(self):
        if self._refs is None:
            raise TypeError("only ref-backed DataIterators are picklable")
        return (DataIterator, (list(self._refs),))
