"""Pull-based streaming executor with bounded in-flight blocks.

reference parity: python/ray/data/_internal/execution/streaming_executor.py
:60 — the reference streams RefBundles between physical operators with
backpressure from ExecutionOptions resource limits. Here the per-block op
chain is fused into ONE task per block (the reference's map fusion), and
backpressure is a hard cap on blocks submitted but not yet consumed, so an
arbitrarily large dataset streams through bounded store memory.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu.data import block as block_mod


def _apply_op(block, op: Tuple) -> Any:
    kind = op[0]
    if kind == "map_batches":
        _, fn, batch_size = op
        if batch_size is None:
            return fn(block)
        n = block_mod.block_num_rows(block)
        outs = [fn(block_mod.slice_block(block, i, min(i + batch_size, n)))
                for i in range(0, n, batch_size)]
        return block_mod.concat_blocks(outs)
    if kind == "map":
        _, fn = op
        return block_mod.rows_to_block(
            [fn(r) for r in block_mod.block_to_rows(block)])
    if kind == "flat_map":
        _, fn = op
        out: List[Any] = []
        for r in block_mod.block_to_rows(block):
            out.extend(fn(r))
        return block_mod.rows_to_block(out)
    if kind == "filter":
        _, fn = op
        return block_mod.rows_to_block(
            [r for r in block_mod.block_to_rows(block) if fn(r)])
    raise ValueError(f"unknown op {kind}")


def _execute_chain(source: Any, ops: List[Tuple]) -> Any:
    """One fused task: build/fetch the input block, run every per-block op."""
    blk = source() if callable(source) else source
    for op in ops:
        blk = _apply_op(blk, op)
    return blk


# Lazily decorated so importing ray_tpu.data stays cheap.
_remote_chain = None


def _get_remote_chain():
    global _remote_chain
    if _remote_chain is None:
        _remote_chain = ray_tpu.remote(_execute_chain)
    return _remote_chain


class StreamingExecutor:
    """Streams (index-ordered) result block refs for `inputs` × `ops`.

    `max_in_flight_blocks` bounds submitted-but-unconsumed blocks: the
    driver does not submit block k+max until block k has been yielded to
    (and therefore consumable by) the caller.
    """

    def __init__(self, inputs: List[Any], ops: List[Tuple], *,
                 max_in_flight_blocks: int = 4,
                 num_cpus_per_task: float = 1.0):
        self.inputs = inputs
        self.ops = ops
        self.max_in_flight = max(1, max_in_flight_blocks)
        self.num_cpus = num_cpus_per_task
        # instrumentation (asserted by backpressure tests)
        self.peak_in_flight = 0
        self._in_flight = 0

    def _submit(self, source: Any):
        remote = _get_remote_chain().options(num_cpus=self.num_cpus)
        ref = remote.remote(source, self.ops)
        self._in_flight += 1
        self.peak_in_flight = max(self.peak_in_flight, self._in_flight)
        return ref

    def execute(self) -> Iterator[Any]:
        """Yield one block ref per input, in input order."""
        if not self.ops:
            # No per-block work: pass through without spawning tasks
            # (materialized refs) or run creation-only tasks for lazy inputs.
            lazy = any(callable(s) for s in self.inputs)
            if not lazy:
                yield from self.inputs
                return
        pending: "deque[Any]" = deque()
        for source in self.inputs:
            while len(pending) >= self.max_in_flight:
                self._in_flight -= 1
                yield pending.popleft()
            pending.append(self._submit(source))
        while pending:
            self._in_flight -= 1
            yield pending.popleft()
