"""Pull-based streaming operator pipeline with per-stage backpressure.

reference parity: python/ray/data/_internal/execution/streaming_executor.py
:60 and execution/interfaces/physical_operator.py:120 — the reference
streams RefBundles between physical operators, each operator holding a
bounded number of running tasks, with backpressure propagating upstream.

Here the plan is a list of *stages*. Consecutive per-block ops
(map/map_batches/filter/flat_map) FUSE into one stage = one task per
block (the reference's map-operator fusion); a stage boundary appears
when an op requests different resources (the reference's fusion rule:
operators with unequal resource requests don't fuse). Stages chain as
generators, so execution is pull-based end to end: nothing runs until
the consumer pulls, stage k+1's tasks start as soon as individual
stage-k blocks finish (no barrier), and each stage's
`max_in_flight` cap propagates backpressure to its upstream — a slow
tail stage stalls the whole pipeline at bounded memory.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu.data import block as block_mod


def _apply_op(block, op: Tuple) -> Any:
    kind = op[0]
    if kind == "map_batches":
        _, fn, batch_size = op
        if batch_size is None:
            return fn(block)
        n = block_mod.block_num_rows(block)
        outs = [fn(block_mod.slice_block(block, i, min(i + batch_size, n)))
                for i in range(0, n, batch_size)]
        return block_mod.concat_blocks(outs)
    if kind == "map":
        _, fn = op
        return block_mod.rows_to_block(
            [fn(r) for r in block_mod.block_to_rows(block)])
    if kind == "flat_map":
        _, fn = op
        out: List[Any] = []
        for r in block_mod.block_to_rows(block):
            out.extend(fn(r))
        return block_mod.rows_to_block(out)
    if kind == "filter":
        _, fn = op
        return block_mod.rows_to_block(
            [r for r in block_mod.block_to_rows(block) if fn(r)])
    raise ValueError(f"unknown op {kind}")


def _execute_chain(source: Any, ops: List[Tuple]) -> Any:
    """One fused task: build/fetch the input block, run every per-block op."""
    blk = source() if callable(source) else source
    for op in ops:
        blk = _apply_op(blk, op)
    return blk


# Lazily decorated so importing ray_tpu.data stays cheap.
_remote_chain = None


def _get_remote_chain():
    global _remote_chain
    if _remote_chain is None:
        _remote_chain = ray_tpu.remote(_execute_chain)
    return _remote_chain


def split_stages(ops: List[Tuple], default_num_cpus: float
                 ) -> List["MapStage"]:
    """Split an op chain into fused stages at ("boundary", num_cpus)
    markers (inserted when a map op requests its own resources)."""
    stages: List[MapStage] = []
    cur: List[Tuple] = []
    cur_cpus = default_num_cpus
    for op in ops:
        if op[0] == "boundary":
            new_cpus = op[1] if op[1] is not None else default_num_cpus
            if new_cpus == cur_cpus:
                continue  # equal resource requests fuse
            if cur:
                stages.append(MapStage(cur, num_cpus=cur_cpus))
                cur = []
            cur_cpus = new_cpus
        else:
            cur.append(op)
    if cur or not stages:
        stages.append(MapStage(cur, num_cpus=cur_cpus))
    return stages


class MapStage:
    """One fused map operator: a bounded pool of per-block tasks.

    reference parity: physical_operator.py:120 (PhysicalOperator with
    num_active_tasks bounded by the resource budget).
    """

    def __init__(self, ops: List[Tuple], *, num_cpus: float = 1.0,
                 max_in_flight: int = 4):
        self.ops = ops
        self.num_cpus = num_cpus
        self.max_in_flight = max(1, max_in_flight)

    def run(self, upstream: Iterator[Any],
            executor: "StreamingExecutor",
            force_tasks: bool = False) -> Iterator[Any]:
        if not self.ops and not force_tasks:
            yield from upstream
            return
        remote = _get_remote_chain().options(num_cpus=self.num_cpus)
        pending: "deque[Any]" = deque()
        for source in upstream:
            while len(pending) >= self.max_in_flight:
                executor._dec()
                yield pending.popleft()
            pending.append(remote.remote(source, self.ops))
            executor._inc()
        while pending:
            executor._dec()
            yield pending.popleft()


class StreamingExecutor:
    """Streams (index-ordered) result block refs for `inputs` x `ops`.

    `ops` may contain ("boundary", num_cpus) markers splitting the chain
    into separately-scheduled stages; per stage, `max_in_flight_blocks`
    bounds submitted-but-unconsumed blocks, and generator chaining makes
    the whole pipeline pull-based — the executor holds at most
    sum(stage caps) live intermediate refs at any moment
    (`peak_in_flight` instruments this; backpressure tests assert on it).
    """

    def __init__(self, inputs: List[Any], ops: List[Tuple], *,
                 max_in_flight_blocks: int = 4,
                 num_cpus_per_task: float = 1.0):
        self.inputs = inputs
        self.ops = ops
        self.max_in_flight = max(1, max_in_flight_blocks)
        self.num_cpus = num_cpus_per_task
        self.stages = split_stages(ops, num_cpus_per_task)
        for st in self.stages:
            st.max_in_flight = self.max_in_flight
        # instrumentation (asserted by backpressure tests): live
        # intermediate refs held across ALL stages
        self.peak_in_flight = 0
        self._in_flight = 0

    def _inc(self):
        self._in_flight += 1
        self.peak_in_flight = max(self.peak_in_flight, self._in_flight)

    def _dec(self):
        self._in_flight -= 1

    def execute(self) -> Iterator[Any]:
        """Yield one block ref per input, in input order."""
        if not any(st.ops for st in self.stages):
            # No per-block work: pass through without spawning tasks
            # (materialized refs) or run creation-only tasks for lazy inputs.
            lazy = any(callable(s) for s in self.inputs)
            if not lazy:
                yield from self.inputs
                return
        lazy = any(callable(s) for s in self.inputs)
        stream: Iterator[Any] = iter(self.inputs)
        for i, st in enumerate(self.stages):
            # lazy sources need a creation task even for an op-less
            # stage so downstream sees block refs, not callables
            stream = st.run(stream, self, force_tasks=(i == 0 and lazy))
        yield from stream
