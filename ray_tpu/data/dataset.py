"""Dataset: lazy block-based data pipeline feeding trainers.

reference parity: python/ray/data/dataset.py — lazy logical plan over
blocks executed by a streaming executor (streaming_executor.py:60) with
map/map_batches/filter/flat_map/repartition/random_shuffle/split, iteration
(iter_rows/iter_batches), and Train integration via per-worker shards
(train/_internal/session.py:1017 get_dataset_shard). Blocks here are
columnar numpy dicts (see block.py) — the shape jax wants.
"""

from __future__ import annotations

import builtins
import math
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

import ray_tpu
from ray_tpu.data import block as block_mod
from ray_tpu.data.block import Block
from ray_tpu.data.executor import StreamingExecutor, _execute_chain
from ray_tpu.data.iterator import DataIterator


class Dataset:
    """A lazy pipeline: input block sources + a chain of per-block ops.

    Per-block ops (map/map_batches/filter/flat_map) fuse into one task per
    block. All-to-all ops (repartition/random_shuffle) materialize.
    """

    def __init__(self, inputs: List[Any], ops: Optional[List] = None):
        self._inputs = inputs
        self._ops = list(ops or [])

    # -- transforms (lazy, fused per block) ---------------------------

    def map(self, fn: Callable[[Dict[str, Any]], Dict[str, Any]], *,
            num_cpus: Optional[float] = None) -> "Dataset":
        return Dataset(self._inputs,
                       self._boundary(num_cpus) + [("map", fn)])

    def map_batches(self, fn: Callable[[Block], Block], *,
                    batch_size: Optional[int] = None,
                    num_cpus: Optional[float] = None) -> "Dataset":
        return Dataset(self._inputs,
                       self._boundary(num_cpus)
                       + [("map_batches", fn, batch_size)])

    def _boundary(self, num_cpus: Optional[float]) -> List:
        """Ops with their own resource request start a new (unfused)
        pipeline stage — the reference's operator-fusion rule (operators
        with unequal resource requests don't fuse; streaming_executor
        then runs them as separate bounded operators)."""
        if num_cpus is None:
            return list(self._ops)
        return self._ops + [("boundary", num_cpus)]

    def flat_map(self, fn: Callable[[Dict[str, Any]], Sequence[Dict]]
                 ) -> "Dataset":
        return Dataset(self._inputs, self._ops + [("flat_map", fn)])

    def filter(self, fn: Callable[[Dict[str, Any]], bool]) -> "Dataset":
        return Dataset(self._inputs, self._ops + [("filter", fn)])

    # -- all-to-all ops (materializing) -------------------------------

    def repartition(self, num_blocks: int) -> "MaterializedDataset":
        """Redistribute rows into `num_blocks` equal-ish blocks."""
        return self._redistribute(num_blocks, shuffle_seed=None)

    def random_shuffle(self, *, seed: Optional[int] = None
                       ) -> "MaterializedDataset":
        """Global row permutation (reference Dataset.random_shuffle)."""
        if seed is None:
            # Fresh entropy per call — a fixed default seed would hand
            # training the same "random" permutation every epoch.
            import os as _os
            seed = int.from_bytes(_os.urandom(4), "big")
        n_out = max(1, len(self._inputs))
        return self._redistribute(n_out, shuffle_seed=seed)

    def _redistribute(self, num_blocks: int,
                      shuffle_seed: Optional[int]) -> "MaterializedDataset":
        mat = self.materialize()
        # Row counts via tiny tasks — don't pull whole blocks to the driver.
        count_remote = ray_tpu.remote(_count_rows)
        counts = ray_tpu.get([count_remote.remote(r) for r in mat._refs])
        total = sum(counts)
        n = num_blocks
        # Balanced bounds (sizes differ by at most 1): ceil-sized partitions
        # would leave trailing partitions empty (e.g. 9 rows / 4 parts →
        # [3,3,3,0]), breaking the every-rank-gets-data invariant SPMD
        # training shards rely on.
        base, extra = divmod(total, n)
        bounds = []
        lo = 0
        for j in builtins.range(n):
            hi = lo + base + (1 if j < extra else 0)
            bounds.append((lo, hi))
            lo = hi
        out_refs = []
        if shuffle_seed is None:
            # Plain repartition keeps global row order, so partition j is
            # the contiguous row range [j*size,(j+1)*size): each task only
            # needs the input blocks overlapping its range — NOT the whole
            # dataset n times over.
            starts = [0]
            for c in counts[:-1]:
                starts.append(starts[-1] + c)
            remote = ray_tpu.remote(_build_partition_contig)
            for lo, hi in bounds:
                sel = [i for i, (s, c) in enumerate(zip(starts, counts))
                       if s < hi and s + c > lo]
                refs_j = [mat._refs[i] for i in sel]
                counts_j = [counts[i] for i in sel]
                gstart = starts[sel[0]] if sel else 0
                out_refs.append(remote.remote(refs_j, counts_j, gstart,
                                              lo, hi))
        else:
            # Global permutation: a true all-to-all; every output needs
            # rows from (potentially) every input.
            remote = ray_tpu.remote(_build_partition)
            out_refs = [
                remote.remote(mat._refs, counts, lo, hi, shuffle_seed)
                for lo, hi in bounds
            ]
        return MaterializedDataset(out_refs)

    def sort(self, key: str, *, descending: bool = False
             ) -> "MaterializedDataset":
        """Distributed sort as a two-phase exchange (reference
        Dataset.sort — sort_sample_keys + map/reduce tasks in
        _internal/planner/exchange/sort_task_spec.py): a map task per
        block range-partitions it by sampled cut points (each block
        crosses the store once, not once per partition), then a reduce
        task per partition merges + locally sorts its pieces."""
        mat = self.materialize()
        n = max(1, len(mat._refs))
        sample_remote = ray_tpu.remote(_sample_keys)
        got = [s for s in ray_tpu.get(
            [sample_remote.remote(r, key) for r in mat._refs])
            if s.size]
        if not got:
            return mat
        samples = np.sort(np.concatenate(got))
        # index-based cut points (works for every comparable dtype,
        # incl. strings, unlike interpolated quantiles)
        cuts = [samples[min(len(samples) - 1,
                            (j * len(samples)) // n)]
                for j in builtins.range(1, n)]
        bounds = np.asarray(cuts)
        split_remote = ray_tpu.remote(_split_by_range) \
            .options(num_returns=n)
        # push-based shuffle (reference _internal/push_based_shuffle.py):
        # map-side range splits tree-merge into per-partition partials
        # round by round, overlapping with later map rounds, so each
        # reducer gets O(maps/merge_factor) refs instead of one per map
        from ray_tpu.data.shuffle import push_based_shuffle
        partials = push_based_shuffle(
            mat._refs, n, split_remote, (key, bounds, n))
        merge_remote = ray_tpu.remote(_merge_sorted)
        refs = [merge_remote.remote(partials[p], key, descending)
                for p in builtins.range(n)]
        if descending:
            refs = refs[::-1]
        return MaterializedDataset(refs)

    def groupby(self, key: str):
        """reference Dataset.groupby -> GroupedData."""
        from ray_tpu.data.grouped import GroupedData
        return GroupedData(self, key)

    def zip(self, other: "Dataset") -> "MaterializedDataset":
        """Column-wise zip of equal-length datasets (reference
        Dataset.zip); the other side is re-sliced to this side's block
        boundaries."""
        left = self.materialize()
        right = other.materialize()
        count_remote = ray_tpu.remote(_count_rows)
        lcounts = ray_tpu.get([count_remote.remote(r)
                               for r in left._refs])
        rcounts = ray_tpu.get([count_remote.remote(r)
                               for r in right._refs])
        if sum(lcounts) != sum(rcounts):
            raise ValueError(
                f"zip needs equal row counts: {sum(lcounts)} vs "
                f"{sum(rcounts)}")
        zip_remote = ray_tpu.remote(_zip_partition)
        refs = []
        lo = 0
        for ref, cnt in zip(left._refs, lcounts):
            refs.append(zip_remote.remote(ref, right._refs, rcounts,
                                          lo, lo + cnt))
            lo += cnt
        return MaterializedDataset(refs)

    def union(self, *others: "Dataset") -> "MaterializedDataset":
        """Row concat (reference Dataset.union)."""
        refs = list(self.materialize()._refs)
        for o in others:
            refs.extend(o.materialize()._refs)
        return MaterializedDataset(refs)

    # -- consumption --------------------------------------------------

    def materialize(self, *, max_in_flight_blocks: int = 4
                    ) -> "MaterializedDataset":
        if isinstance(self, MaterializedDataset) and not self._ops:
            return self
        ex = StreamingExecutor(self._inputs, self._ops,
                               max_in_flight_blocks=max_in_flight_blocks)
        return MaterializedDataset(list(ex.execute()))

    def iter_blocks(self, *, max_in_flight_blocks: int = 4) -> Iterator[Block]:
        ex = StreamingExecutor(self._inputs, self._ops,
                               max_in_flight_blocks=max_in_flight_blocks)
        for ref in ex.execute():
            # streaming: one block in memory at a time is the point
            # graftlint: disable=RT002
            yield ray_tpu.get(ref) if isinstance(ref, ray_tpu.ObjectRef) \
                else ref

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for blk in self.iter_blocks():
            yield from block_mod.block_to_rows(blk)

    def iter_batches(self, *, batch_size: int = 256, drop_last: bool = False,
                     max_in_flight_blocks: int = 4) -> Iterator[Block]:
        it = DataIterator(blocks=self.iter_blocks(
            max_in_flight_blocks=max_in_flight_blocks))
        yield from it.iter_batches(batch_size=batch_size, drop_last=drop_last)

    def iter_torch_batches(self, *, batch_size: int = 256,
                           drop_last: bool = False,
                           device: Optional[str] = None):
        """Batches as dicts of torch tensors (reference
        Dataset.iter_torch_batches)."""
        import torch
        for blk in self.iter_batches(batch_size=batch_size,
                                     drop_last=drop_last):
            out = {}
            for k, v in blk.items():
                arr = np.ascontiguousarray(v)
                if not arr.flags.writeable:
                    # store-backed blocks are read-only shm views;
                    # torch requires writable memory
                    arr = arr.copy()
                t = torch.as_tensor(arr)
                out[k] = t.to(device) if device else t
            yield out

    def take(self, k: int = 20) -> List[Dict[str, Any]]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= k:
                break
        return out

    def count(self) -> int:
        # Counting never needs block payloads in the driver: materialize,
        # then sum row counts via tiny tasks.
        mat = self.materialize()
        refs = [r for r in mat._refs if isinstance(r, ray_tpu.ObjectRef)]
        if len(refs) != len(mat._refs):
            return sum(block_mod.block_num_rows(b)
                       for b in mat.iter_blocks())
        count_remote = ray_tpu.remote(_count_rows)
        return sum(ray_tpu.get([count_remote.remote(r) for r in refs]))

    # whole-dataset aggregates (reference Dataset.sum/min/max/mean/std):
    # per-block partials via tiny tasks, combined on the driver
    def _agg(self, on: str, kind: str):
        mat = self.materialize()
        remote = ray_tpu.remote(_block_partial_agg)
        parts = [p for p in ray_tpu.get(
            [remote.remote(r, on, kind) for r in mat._refs])
            if p is not None]
        if not parts:
            raise ValueError(f"cannot aggregate empty dataset on {on!r}")
        if kind == "sum":
            return sum(p[0] for p in parts)
        if kind == "min":
            return min(p[0] for p in parts)
        if kind == "max":
            return max(p[0] for p in parts)
        if kind == "mean":
            n = sum(p[1] for p in parts)
            return sum(p[0] for p in parts) / n
        # std: merge per-block (n, mean, M2) with Chan's parallel
        # update — a global E[x^2]-mean^2 would cancel catastrophically
        # for large-mean data. ddof=1 (sample std) matches the
        # reference Dataset.std and this repo's GroupedData.std.
        n, mean, m2 = parts[0]
        for nb, mb, m2b in parts[1:]:
            delta = mb - mean
            tot = n + nb
            mean += delta * nb / tot
            m2 += m2b + delta * delta * n * nb / tot
            n = tot
        if n < 2:
            return 0.0
        return float(np.sqrt(m2 / (n - 1)))

    def sum(self, on: str):  # noqa: A003
        return self._agg(on, "sum")

    def min(self, on: str):  # noqa: A003
        return self._agg(on, "min")

    def max(self, on: str):  # noqa: A003
        return self._agg(on, "max")

    def mean(self, on: str):
        return self._agg(on, "mean")

    def std(self, on: str):
        return self._agg(on, "std")

    def schema(self) -> Dict[str, str]:
        for blk in self.iter_blocks():
            if block_mod.block_num_rows(blk):
                return block_mod.block_schema(blk)
        return {}

    # -- train integration --------------------------------------------

    def split(self, n: int, *, equal: bool = False
              ) -> List["MaterializedDataset"]:
        """N disjoint shards, one per train worker (reference
        Dataset.split / streaming_split feeding get_dataset_shard)."""
        mat = self.repartition(n) if equal else self.materialize()
        shards: List[List[Any]] = [[] for _ in builtins.range(n)]
        for i, ref in enumerate(mat._refs):
            shards[i % n].append(ref)
        return [MaterializedDataset(refs) for refs in shards]

    def num_blocks(self) -> int:
        return len(self._inputs)

    def __repr__(self) -> str:
        return (f"{type(self).__name__}(num_blocks={len(self._inputs)}, "
                f"ops={[o[0] for o in self._ops]})")


class MaterializedDataset(Dataset):
    """All blocks computed and living in the object store as refs."""

    def __init__(self, refs: List[Any]):
        super().__init__(refs, [])
        self._refs = refs

    def iterator(self) -> DataIterator:
        return DataIterator(refs=list(self._refs))


def _count_rows(blk: Block) -> int:
    return block_mod.block_num_rows(blk)


def _block_partial_agg(blk: Block, on: str, kind: str):
    """Per-block partials; None if empty. sum/min/max: (value,);
    mean: (total, count); std: (count, mean, M2)."""
    if not block_mod.block_num_rows(blk):
        return None
    col = np.asarray(blk[on])
    if kind == "sum":
        return (col.sum(),)
    if kind == "min":
        return (col.min(),)
    if kind == "max":
        return (col.max(),)
    if kind == "mean":
        return (float(col.sum()), int(col.size))
    mean = float(col.mean())
    m2 = float(((col.astype(np.float64) - mean) ** 2).sum())
    return (int(col.size), mean, m2)


def _sample_keys(blk: Block, key: str, max_samples: int = 100
                 ) -> np.ndarray:
    if not block_mod.block_num_rows(blk):
        return np.asarray([])
    col = np.asarray(blk[key])
    if len(col) <= max_samples:
        return col
    idx = np.random.default_rng(0).choice(len(col), max_samples,
                                          replace=False)
    return col[idx]


def _split_by_range(blk: Block, key: str, bounds: np.ndarray, n: int):
    """Map phase: one piece per output partition. NaN keys fall through
    searchsorted to the last partition (never silently dropped)."""
    if not block_mod.block_num_rows(blk):
        return tuple({} for _ in builtins.range(n))
    part_ids = np.searchsorted(bounds, np.asarray(blk[key]),
                               side="right")
    return tuple(
        block_mod.take_rows(blk, np.nonzero(part_ids == p)[0])
        for p in builtins.range(n))


def _merge_sorted(refs: List[Any], key: str, descending: bool) -> Block:
    """Reduce phase: merge this partition's pieces and sort locally."""
    pieces = [b for b in ray_tpu.get(list(refs))
              if block_mod.block_num_rows(b)]
    merged = block_mod.concat_blocks(pieces)
    if not block_mod.block_num_rows(merged):
        return merged
    order = np.argsort(merged[key], kind="stable")
    if descending:
        order = order[::-1]
    return block_mod.take_rows(merged, order)


def _zip_partition(left_blk: Block, right_refs: List[Any],
                   rcounts: List[int], lo: int, hi: int) -> Block:
    """Zip the left block with the right side's global rows [lo,hi)."""
    overlaps = []
    pos = 0
    for ref, cnt in zip(right_refs, rcounts):
        s, e = max(lo, pos), min(hi, pos + cnt)
        if e > s:
            overlaps.append((ref, s - pos, e - pos))
        pos += cnt
    # one batched get for every overlapping block (found by graftlint
    # RT002: a get per block serialized the fetches)
    blocks = ray_tpu.get([ref for ref, _, _ in overlaps])
    pieces = [block_mod.slice_block(blk, s0, e0)
              for blk, (_, s0, e0) in zip(blocks, overlaps)]
    right = block_mod.concat_blocks(pieces)
    out = dict(left_blk)
    for k, v in right.items():
        name = k
        suffix = 1
        while name in out:  # probe a free suffix, never clobber
            name = f"{k}_{suffix}"
            suffix += 1
        out[name] = v
    return out


def _build_partition_contig(refs: List[Any], counts: List[int],
                            gstart: int, lo: int, hi: int) -> Block:
    """Assemble contiguous global row range [lo,hi) from the (overlapping)
    input blocks, whose first block starts at global row `gstart`."""
    blocks = ray_tpu.get(list(refs))
    pieces = []
    pos = gstart
    for blk, cnt in zip(blocks, counts):
        s, e = max(lo, pos), min(hi, pos + cnt)
        if e > s:
            pieces.append(block_mod.slice_block(blk, s - pos, e - pos))
        pos += cnt
    return block_mod.concat_blocks(pieces)


def _build_partition(refs: List[Any], counts: List[int], lo: int, hi: int,
                     shuffle_seed: Optional[int]) -> Block:
    """Worker-side: assemble the output rows [lo,hi) of the (optionally
    permuted) global row order from all input blocks."""
    blocks = ray_tpu.get(list(refs))
    total = sum(counts)
    ids = np.arange(total)
    if shuffle_seed is not None:
        ids = np.random.default_rng(shuffle_seed).permutation(total)
    mine = ids[lo:hi]
    mine_sorted = np.sort(mine) if shuffle_seed is None else mine
    # map global row id -> (block, local row)
    starts = np.cumsum([0] + counts[:-1])
    pieces = []
    for blk, start, cnt in zip(blocks, starts, counts):
        sel = mine_sorted[(mine_sorted >= start) & (mine_sorted < start + cnt)]
        if len(sel):
            pieces.append(block_mod.take_rows(blk, sel - start))
    return block_mod.concat_blocks(pieces)


# -- creation APIs (reference ray.data.from_items / range / from_numpy) ----

def _chunk_bounds(n: int, parallelism: int) -> List[tuple]:
    parallelism = max(1, min(parallelism, n)) if n else 1
    size = math.ceil(n / parallelism) if n else 0
    # builtins.range: the module-level `range` below shadows the builtin
    return [(i, min(i + size, n))
            for i in builtins.range(0, n, size)] if n else []


def from_items(items: Sequence[Any], *, parallelism: int = 8) -> Dataset:
    bounds = _chunk_bounds(len(items), parallelism)
    inputs = [_ItemsSource(list(items[a:b])) for a, b in bounds]
    return Dataset(inputs)


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    bounds = _chunk_bounds(n, parallelism)
    return Dataset([_RangeSource(a, b) for a, b in bounds])


def from_numpy(arrays: Dict[str, np.ndarray], *,
               parallelism: int = 8) -> Dataset:
    n = len(next(iter(arrays.values()))) if arrays else 0
    bounds = _chunk_bounds(n, parallelism)
    return Dataset([
        _ItemsBlockSource({k: v[a:b] for k, v in arrays.items()})
        for a, b in bounds])


def from_blocks(blocks: Sequence[Block]) -> Dataset:
    return Dataset([_ItemsBlockSource(dict(b)) for b in blocks])


class _RangeSource:
    """Picklable lazy block: np.arange slice built inside the task."""

    def __init__(self, start: int, stop: int):
        self.start, self.stop = start, stop

    def __call__(self) -> Block:
        return {"id": np.arange(self.start, self.stop)}


class _ItemsSource:
    def __init__(self, items: List[Any]):
        self.items = items

    def __call__(self) -> Block:
        return block_mod.rows_to_block(self.items)


class _ItemsBlockSource:
    def __init__(self, blk: Block):
        self.blk = blk

    def __call__(self) -> Block:
        return self.blk
