"""File IO: csv / jsonl / parquet readers+writers, pandas interop.

reference parity: python/ray/data/read_api.py (read_csv/read_json/
read_parquet — one read task per file) and Dataset.write_* (one write
task per block producing part files). pandas + pyarrow do the parsing,
as in the reference's datasource implementations.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

import ray_tpu
from ray_tpu.data import block as block_mod
from ray_tpu.data.block import Block
from ray_tpu.data.dataset import Dataset, MaterializedDataset


def _expand(paths: Union[str, Sequence[str]], suffix: str) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if f.endswith(suffix)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no {suffix} files under {paths}")
    return out


def _df_to_block(df) -> Block:
    return {c: df[c].to_numpy() for c in df.columns}


def _block_to_df(blk: Block):
    import pandas as pd
    return pd.DataFrame(dict(blk))


class _FileSource:
    """Picklable lazy block source: parse one file inside the task."""

    def __init__(self, path: str, fmt: str):
        self.path, self.fmt = path, fmt

    def __call__(self) -> Block:
        import pandas as pd
        if self.fmt == "csv":
            return _df_to_block(pd.read_csv(self.path))
        if self.fmt == "json":
            return _df_to_block(pd.read_json(self.path, lines=True))
        if self.fmt == "parquet":
            import pyarrow.parquet as pq
            tbl = pq.read_table(self.path)
            return {c: tbl[c].to_numpy(zero_copy_only=False)
                    for c in tbl.column_names}
        raise ValueError(f"unknown format {self.fmt}")


def read_csv(paths: Union[str, Sequence[str]]) -> Dataset:
    return Dataset([_FileSource(p, "csv")
                    for p in _expand(paths, ".csv")])


def read_json(paths: Union[str, Sequence[str]]) -> Dataset:
    """JSONL (one object per line), like the reference's JSON datasource."""
    files = [p for suf in (".json", ".jsonl")
             for p in _try_expand(paths, suf)]
    if not files:
        raise FileNotFoundError(f"no json files under {paths}")
    return Dataset([_FileSource(p, "json") for p in dict.fromkeys(files)])


def _try_expand(paths, suffix):
    try:
        return _expand(paths, suffix)
    except FileNotFoundError:
        return []


def read_parquet(paths: Union[str, Sequence[str]]) -> Dataset:
    return Dataset([_FileSource(p, "parquet")
                    for p in _expand(paths, ".parquet")])


def from_pandas(dfs) -> Dataset:
    """One block per DataFrame (reference ray.data.from_pandas)."""
    if not isinstance(dfs, (list, tuple)):
        dfs = [dfs]
    from ray_tpu.data.dataset import from_blocks
    return from_blocks([_df_to_block(df) for df in dfs])


def _write_block(blk: Block, path: str, fmt: str, index: int) -> str:
    os.makedirs(path, exist_ok=True)
    name = os.path.join(path, f"part-{index:05d}.{fmt}")
    df = _block_to_df(blk)
    if fmt == "csv":
        df.to_csv(name, index=False)
    elif fmt == "json":
        df.to_json(name, orient="records", lines=True)
    elif fmt == "parquet":
        import pyarrow as pa
        import pyarrow.parquet as pq
        pq.write_table(pa.Table.from_pandas(df, preserve_index=False),
                       name)
    return name


def _write(ds: Dataset, path: str, fmt: str) -> List[str]:
    mat = ds.materialize()
    remote = ray_tpu.remote(_write_block)
    return ray_tpu.get([
        remote.remote(ref, path, fmt, i)
        for i, ref in enumerate(mat._refs)])


# Dataset methods (attached in dataset.py would be circular; patch here)
def write_csv(self: Dataset, path: str) -> List[str]:
    return _write(self, path, "csv")


def write_json(self: Dataset, path: str) -> List[str]:
    return _write(self, path, "json")


def write_parquet(self: Dataset, path: str) -> List[str]:
    return _write(self, path, "parquet")


def to_pandas(self: Dataset, limit: Optional[int] = None):
    import pandas as pd
    dfs = [_block_to_df(b) for b in self.iter_blocks()
           if block_mod.block_num_rows(b)]
    df = pd.concat(dfs, ignore_index=True) if dfs else pd.DataFrame()
    return df.head(limit) if limit is not None else df


Dataset.write_csv = write_csv
Dataset.write_json = write_json
Dataset.write_parquet = write_parquet
Dataset.to_pandas = to_pandas
