"""Tuner: the public experiment API over TuneController.

reference parity: python/ray/tune/tuner.py:54 (Tuner.fit → ResultGrid)
+ tune/tune.py run(). Accepts a function trainable, a Trainable subclass,
an rllib AlgorithmConfig (variants merge into .training(**cfg)), or a
DataParallelTrainer instance (variants merge into train_loop_config).
"""

from __future__ import annotations

import inspect
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.tune.search import BasicVariantGenerator
from ray_tpu.tune.trainable import Trainable, wrap_function
from ray_tpu.tune.tune_controller import TuneController


@dataclass
class TuneConfig:
    """reference tune/tune_config.py TuneConfig."""

    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    scheduler: Any = None
    search_seed: Optional[int] = None
    # sequential search algorithm (a tune.search.Searcher, e.g.
    # TPESearcher / OptunaSearcher); when set, num_samples trials are
    # suggested one-by-one with results fed back (reference search_alg)
    search_alg: Any = None


@dataclass
class TuneRunConfig:
    """Experiment-level config (reference air RunConfig for Tune runs)."""

    name: str = ""
    storage_path: str = "/tmp/ray_tpu_results"
    stop: Optional[Dict[str, Any]] = None
    max_failures_per_trial: int = 1
    checkpoint_frequency: int = 0
    resources_per_trial: Dict[str, float] = field(
        default_factory=lambda: {"CPU": 1.0})


@dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Dict[str, Any]
    metrics_history: List[Dict[str, Any]]
    checkpoint_dir: Optional[str]
    error: Optional[BaseException]
    state: str
    num_restores: int = 0


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: Optional[str],
                 mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self) -> int:
        return len(self._results)

    def __iter__(self):
        return iter(self._results)

    def __getitem__(self, i: int) -> TrialResult:
        return self._results[i]

    @property
    def errors(self) -> List[BaseException]:
        return [r.error for r in self._results if r.error is not None]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("no metric given (TuneConfig.metric or arg)")
        scored = [r for r in self._results if metric in r.metrics]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        return (max if mode == "max" else min)(
            scored, key=lambda r: r.metrics[metric])


def _make_factory(trainable: Any) -> Callable[[Dict[str, Any]], Any]:
    """Normalize the four accepted trainable kinds into factory(config)."""
    from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
    if isinstance(trainable, AlgorithmConfig):
        base = trainable

        def algo_factory(config: Dict[str, Any]):
            return base.copy().training(**config).build()
        return algo_factory
    from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
    if isinstance(trainable, DataParallelTrainer):
        return _TrainerTrainableFactory(trainable)
    if inspect.isclass(trainable) and issubclass(trainable, Trainable):
        return trainable
    if callable(trainable):
        return wrap_function(trainable)
    raise TypeError(f"unsupported trainable: {trainable!r}")


class _TrainerTrainableFactory:
    """Each trial clones the trainer with the variant merged into
    train_loop_config and fit()s it once (reference
    BaseTrainer.as_trainable, base_trainer.py:839)."""

    def __init__(self, trainer: Any):
        self._trainer = trainer

    def __call__(self, config: Dict[str, Any]):
        import copy

        trainer = copy.copy(self._trainer)
        merged = dict(trainer._train_loop_config or {})
        merged.update(config)
        trainer._train_loop_config = merged

        class _OneShot(Trainable):
            def step(inner) -> Dict[str, Any]:
                result = trainer.fit()
                if result.error is not None:
                    raise result.error
                out = dict(result.metrics)
                out["done"] = True
                inner._result = result
                return out

        return _OneShot(config)


class Tuner:
    def __init__(self, trainable: Any, *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[TuneRunConfig] = None):
        self._trainable = trainable
        self._param_space = dict(param_space or {})
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config or TuneRunConfig()
        self._resume_state: Optional[Dict[str, Any]] = None
        self._resume_dir: Optional[str] = None

    @classmethod
    def restore(cls, path: str, trainable: Any) -> "Tuner":
        """Resume an interrupted/failed experiment from its run dir
        (reference Tuner.restore, tuner.py). Finished trials keep their
        results; unfinished or errored trials rerun, restoring from
        their latest checkpoint when one exists. The original
        tune/run configs reload from the run dir."""
        import pickle
        state_file = os.path.join(path, "experiment_state.pkl")
        with open(state_file, "rb") as f:
            state = pickle.load(f)
        tune_config = run_config = None
        meta_file = os.path.join(path, "tuner_config.pkl")
        if os.path.exists(meta_file):
            with open(meta_file, "rb") as f:
                meta = pickle.load(f)
            tune_config = meta.get("tune_config")
            run_config = meta.get("run_config")
        else:
            import logging
            logging.getLogger(__name__).warning(
                "no tuner_config.pkl under %s (original configs were "
                "unpicklable?) — resuming with DEFAULT TuneConfig/"
                "TuneRunConfig: no scheduler, no stop conditions", path)
        tuner = cls(trainable, tune_config=tune_config,
                    run_config=run_config)
        tuner._resume_state = state
        tuner._resume_dir = path
        return tuner

    def fit(self) -> ResultGrid:
        tc, rc = self._tune_config, self._run_config
        if self._resume_dir:
            run_dir = self._resume_dir
            variants = [t["config"]
                        for t in self._resume_state["trials"]]
        else:
            name = rc.name or f"tune_{time.strftime('%Y%m%d_%H%M%S')}"
            run_dir = os.path.join(rc.storage_path, name)
            os.makedirs(run_dir, exist_ok=True)
            variants = [] if tc.search_alg is not None else list(
                BasicVariantGenerator(
                    self._param_space, num_samples=tc.num_samples,
                    seed=tc.search_seed).variants())
            import pickle
            try:
                with open(os.path.join(run_dir, "tuner_config.pkl"),
                          "wb") as f:
                    pickle.dump({"tune_config": tc, "run_config": rc,
                                 "param_space": self._param_space}, f)
            except Exception:  # noqa: BLE001 — unpicklable scheduler etc.
                pass
        controller = TuneController(
            _make_factory(self._trainable), variants,
            run_dir=run_dir, stop=rc.stop, scheduler=tc.scheduler,
            max_concurrent_trials=tc.max_concurrent_trials,
            max_failures_per_trial=rc.max_failures_per_trial,
            checkpoint_frequency=rc.checkpoint_frequency,
            resources_per_trial=rc.resources_per_trial,
            resume_state=self._resume_state,
            searcher=tc.search_alg,
            num_searcher_trials=(tc.num_samples
                                 if tc.search_alg is not None else 0))
        trials = controller.run()
        results = [
            TrialResult(
                trial_id=t.trial_id, config=t.config,
                metrics=t.last_result, metrics_history=t.results,
                checkpoint_dir=t.checkpoint_dir, error=t.error,
                state=t.state, num_restores=t.num_restores)
            for t in trials
        ]
        return ResultGrid(results, tc.metric, tc.mode)
