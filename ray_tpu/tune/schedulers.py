"""Trial schedulers: FIFO (run to stop condition) + ASHA early stopping.

reference parity: python/ray/tune/schedulers/ — FIFOScheduler and
AsyncHyperBandScheduler/ASHA (async_hyperband.py): rungs at
grace_period * reduction_factor^k; a trial reaching a rung must be in the
top 1/reduction_factor of completed results at that rung or it stops.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        return CONTINUE


class ASHAScheduler:
    def __init__(self, *, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace = grace_period
        self.rf = reduction_factor
        # rung milestone -> list of recorded metric values at that rung
        self._rungs: Dict[int, list] = defaultdict(list)
        milestones = []
        t = grace_period
        while t < max_t:
            milestones.append(t)
            t *= reduction_factor
        self._milestones = milestones

    def on_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        decision = CONTINUE
        for milestone in self._milestones:
            if t == milestone:
                recorded = self._rungs[milestone]
                recorded.append(value)
                ranked = sorted(recorded, reverse=(self.mode == "max"))
                # Keep the top len//rf (>=1) at this rung; an early arrival
                # with no peers is promoted optimistically (async ASHA).
                keep = max(1, len(ranked) // self.rf)
                if len(ranked) >= self.rf and \
                        ranked.index(value) >= keep:
                    decision = STOP
        return decision
