"""Trial schedulers: FIFO, ASHA, median stopping, PBT.

reference parity: python/ray/tune/schedulers/ — FIFOScheduler,
AsyncHyperBandScheduler/ASHA (async_hyperband.py: rungs at
grace_period * reduction_factor^k; a trial reaching a rung must be in the
top 1/reduction_factor of completed results at that rung or it stops),
MedianStoppingRule (median_stopping_rule.py), and
PopulationBasedTraining (pbt.py: bottom-quantile trials clone a
top-quantile trial's checkpoint and perturb its hyperparams).

Decision protocol: on_result returns CONTINUE, STOP, or an exploit dict
{"action": "exploit", "source": trial_id, "config": {...}} that the
controller executes by cloning the source's checkpoint into the trial.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        return CONTINUE


class ASHAScheduler:
    def __init__(self, *, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace = grace_period
        self.rf = reduction_factor
        # rung milestone -> list of recorded metric values at that rung
        self._rungs: Dict[int, list] = defaultdict(list)
        milestones = []
        t = grace_period
        while t < max_t:
            milestones.append(t)
            t *= reduction_factor
        self._milestones = milestones

    def on_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        decision = CONTINUE
        for milestone in self._milestones:
            if t == milestone:
                recorded = self._rungs[milestone]
                recorded.append(value)
                ranked = sorted(recorded, reverse=(self.mode == "max"))
                # Keep the top len//rf (>=1) at this rung; an early arrival
                # with no peers is promoted optimistically (async ASHA).
                keep = max(1, len(ranked) // self.rf)
                if len(ranked) >= self.rf and \
                        ranked.index(value) >= keep:
                    decision = STOP
        return decision


class MedianStoppingRule:
    """Stop a trial whose running-average metric falls below the median
    of other trials' running averages at the same step (reference
    schedulers/median_stopping_rule.py)."""

    def __init__(self, *, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace = grace_period
        self.min_samples = min_samples_required
        # per trial: list of (step, value) so comparisons are
        # step-aligned (a late-starting trial is judged against what
        # others had achieved BY the same step, not their mature means)
        self._history: Dict[str, List[tuple]] = defaultdict(list)

    def _mean_up_to(self, trial_id: str, t: float) -> Optional[float]:
        vals = [v for (s, v) in self._history[trial_id] if s <= t]
        return float(np.mean(vals)) if vals else None

    def on_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        value = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if value is None:
            return CONTINUE
        self._history[trial_id].append((t, float(value)))
        if t < self.grace:
            return CONTINUE
        others = [m for k in self._history if k != trial_id
                  for m in [self._mean_up_to(k, t)] if m is not None]
        if len(others) < self.min_samples:
            return CONTINUE
        median = float(np.median(others))
        mine = self._mean_up_to(trial_id, t)
        worse = mine < median if self.mode == "max" else mine > median
        return STOP if worse else CONTINUE


MutationSpace = Union[Sequence[Any], Callable[[], Any]]


class PopulationBasedTraining:
    """PBT (reference schedulers/pbt.py): every perturbation_interval, a
    bottom-quantile trial exploits (clones checkpoint + config of) a
    random top-quantile trial and explores (perturbs the hyperparams —
    resample from the mutation space with resample_probability, else
    scale numerics by 1.2/0.8 or hop to a neighboring choice)."""

    def __init__(self, *, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Dict[str, MutationSpace],
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        assert mode in ("max", "min")
        assert 0 < quantile_fraction <= 0.5
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = dict(hyperparam_mutations)
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self._rng = np.random.default_rng(seed)
        self._configs: Dict[str, Dict[str, Any]] = {}
        self._scores: Dict[str, float] = {}
        self._last_perturb: Dict[str, float] = {}
        self.num_perturbations = 0

    # controller calls this for every trial before the loop starts
    def on_trial_add(self, trial_id: str,
                     config: Dict[str, Any]) -> None:
        self._configs[trial_id] = dict(config)
        self._last_perturb.setdefault(trial_id, 0)

    # controller calls this when a trial terminates/errors so the
    # population gate tracks LIVE trials (a dead trial that never
    # reports would otherwise freeze PBT into FIFO forever)
    def on_trial_remove(self, trial_id: str) -> None:
        self._configs.pop(trial_id, None)
        self._scores.pop(trial_id, None)

    # controller confirms a successfully-applied exploit; only then
    # does the scheduler's config view (and the perturb counter) move
    def confirm_exploit(self, trial_id: str,
                        config: Dict[str, Any]) -> None:
        self._configs[trial_id] = dict(config)
        self.num_perturbations += 1

    def _resample(self, space: MutationSpace) -> Any:
        if callable(space):
            return space()
        return space[self._rng.integers(len(space))]

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(config)
        for key, space in self.mutations.items():
            cur = out.get(key)
            if self._rng.random() < self.resample_prob or cur is None:
                out[key] = self._resample(space)
            elif not callable(space) and cur in list(space):
                # choice list: hop to a neighboring value (reference
                # pbt explore picks an adjacent index for lists)
                ix = list(space).index(cur)
                ix = int(np.clip(
                    ix + self._rng.choice([-1, 1]), 0, len(space) - 1))
                out[key] = list(space)[ix]
            elif isinstance(cur, (int, float)):
                # continuous space: scale by 1.2 / 0.8
                factor = 1.2 if self._rng.random() < 0.5 else 0.8
                if isinstance(cur, float):
                    out[key] = type(cur)(cur * factor)
                else:
                    nxt = int(round(cur * factor))
                    if nxt == cur:
                        # small ints: truncation would pin the value
                        # forever; force a step of 1 in the chosen
                        # direction instead
                        nxt = cur + 1 if factor > 1 else cur - 1
                    out[key] = max(1, nxt)
            else:
                out[key] = self._resample(space)
        return out

    def on_result(self, trial_id: str, result: Dict[str, Any]):
        value = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if value is None:
            return CONTINUE
        self._scores[trial_id] = float(value)
        self._configs.setdefault(trial_id, {})
        if t - self._last_perturb.get(trial_id, 0) < self.interval:
            return CONTINUE
        self._last_perturb[trial_id] = t
        # wait until the whole registered population has reported —
        # quantiles over a partial population exploit prematurely
        population = max(2, len(self._configs))
        if len(self._scores) < population:
            return CONTINUE
        ordered = sorted(self._scores,
                         key=lambda k: self._scores[k],
                         reverse=(self.mode == "max"))
        k = max(1, int(len(ordered) * self.quantile))
        top, bottom = ordered[:k], ordered[-k:]
        if trial_id not in bottom or trial_id in top:
            return CONTINUE
        candidates = [s for s in top if s != trial_id]
        src = candidates[self._rng.integers(len(candidates))]
        new_config = self._explore(self._configs[src])
        # proposal only — the controller calls confirm_exploit once the
        # checkpoint clone actually succeeds
        return {"action": "exploit", "source": src,
                "config": new_config}
