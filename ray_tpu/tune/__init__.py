"""ray_tpu.tune: trial-based experiment execution (Tune equivalent).

reference parity: python/ray/tune — Tuner/TuneController over the
Trainable step/save/restore contract, grid+random search, ASHA scheduler,
per-trial failure retry from checkpoint.
"""

from ray_tpu.tune.schedulers import (ASHAScheduler, FIFOScheduler,  # noqa: F401
                                     MedianStoppingRule,
                                     PopulationBasedTraining)
from ray_tpu.tune.search import (OptunaSearcher, Searcher,  # noqa: F401
                                 TPESearcher)
from ray_tpu.tune.search import (choice, grid_search, loguniform,  # noqa: F401
                                 randint, uniform)
from ray_tpu.tune.trainable import (FunctionTrainable, Trainable,  # noqa: F401
                                    report, wrap_function)
from ray_tpu.tune.tuner import (ResultGrid, TrialResult, TuneConfig,  # noqa: F401
                                TuneRunConfig, Tuner)

__all__ = [
    "Tuner", "TuneConfig", "TuneRunConfig", "ResultGrid", "TrialResult",
    "Trainable", "FunctionTrainable", "wrap_function", "report",
    "grid_search", "choice", "uniform", "loguniform", "randint",
    "ASHAScheduler", "FIFOScheduler", "MedianStoppingRule",
    "PopulationBasedTraining",
]

from ray_tpu._private.usage import record_library_usage as _rlu
_rlu('tune')
del _rlu
