"""Search spaces + variant generation (grid + random sampling).

reference parity: python/ray/tune/search/ — BasicVariantGenerator
(search/basic_variant.py) expanding tune.grid_search over the cross
product and sampling Domain objects (search/sample.py: choice/uniform/
loguniform/randint) num_samples times.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, Iterator, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Choice(Domain):
    def __init__(self, categories: List[Any]):
        self.categories = list(categories)

    def sample(self, rng: random.Random) -> Any:
        return rng.choice(self.categories)


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng: random.Random) -> float:
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        import math
        self._lo, self._hi = math.log(low), math.log(high)

    def sample(self, rng: random.Random) -> float:
        import math
        return math.exp(rng.uniform(self._lo, self._hi))


class RandInt(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng: random.Random) -> int:
        return rng.randrange(self.low, self.high)


class GridSearch:
    def __init__(self, values: List[Any]):
        self.values = list(values)


# -- public space constructors (reference tune.grid_search/choice/...) ----

def grid_search(values: List[Any]) -> GridSearch:
    return GridSearch(values)


def choice(categories: List[Any]) -> Choice:
    return Choice(categories)


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


class BasicVariantGenerator:
    """Cross product of grid_search entries × num_samples random draws of
    Domain entries (reference search/basic_variant.py)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self.param_space = dict(param_space)
        self.num_samples = num_samples
        self._rng = random.Random(seed)

    def variants(self) -> Iterator[Dict[str, Any]]:
        grid_keys = [k for k, v in self.param_space.items()
                     if isinstance(v, GridSearch)]
        grid_values = [self.param_space[k].values for k in grid_keys]
        has_domains = any(isinstance(v, Domain)
                          for v in self.param_space.values())
        repeats = self.num_samples if (has_domains or not grid_keys) else 1
        for _ in range(repeats):
            for combo in itertools.product(*grid_values) if grid_keys \
                    else [()]:
                cfg: Dict[str, Any] = {}
                for k, v in self.param_space.items():
                    if isinstance(v, GridSearch):
                        cfg[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self._rng)
                    else:
                        cfg[k] = v
                yield cfg
