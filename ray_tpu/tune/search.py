"""Search spaces + variant generation (grid + random sampling).

reference parity: python/ray/tune/search/ — BasicVariantGenerator
(search/basic_variant.py) expanding tune.grid_search over the cross
product and sampling Domain objects (search/sample.py: choice/uniform/
loguniform/randint) num_samples times.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, Iterator, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Choice(Domain):
    def __init__(self, categories: List[Any]):
        self.categories = list(categories)

    def sample(self, rng: random.Random) -> Any:
        return rng.choice(self.categories)


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng: random.Random) -> float:
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        import math
        self._lo, self._hi = math.log(low), math.log(high)

    def sample(self, rng: random.Random) -> float:
        import math
        return math.exp(rng.uniform(self._lo, self._hi))


class RandInt(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng: random.Random) -> int:
        return rng.randrange(self.low, self.high)


class GridSearch:
    def __init__(self, values: List[Any]):
        self.values = list(values)


# -- public space constructors (reference tune.grid_search/choice/...) ----

def grid_search(values: List[Any]) -> GridSearch:
    return GridSearch(values)


def choice(categories: List[Any]) -> Choice:
    return Choice(categories)


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


class BasicVariantGenerator:
    """Cross product of grid_search entries × num_samples random draws of
    Domain entries (reference search/basic_variant.py)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self.param_space = dict(param_space)
        self.num_samples = num_samples
        self._rng = random.Random(seed)

    def variants(self) -> Iterator[Dict[str, Any]]:
        grid_keys = [k for k, v in self.param_space.items()
                     if isinstance(v, GridSearch)]
        grid_values = [self.param_space[k].values for k in grid_keys]
        has_domains = any(isinstance(v, Domain)
                          for v in self.param_space.values())
        repeats = self.num_samples if (has_domains or not grid_keys) else 1
        for _ in range(repeats):
            for combo in itertools.product(*grid_values) if grid_keys \
                    else [()]:
                cfg: Dict[str, Any] = {}
                for k, v in self.param_space.items():
                    if isinstance(v, GridSearch):
                        cfg[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self._rng)
                    else:
                        cfg[k] = v
                yield cfg


# -- Searcher interface (reference tune/search/searcher.py) ---------------

class Searcher:
    """Sequential search algorithm: the controller asks for one config
    per new trial and reports results back (reference Searcher ABC —
    the adapter surface optuna/hyperopt integrations plug into)."""

    def __init__(self, metric: str = "score", mode: str = "max"):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        pass

    def save(self) -> Dict[str, Any]:
        return {}

    def restore(self, state: Dict[str, Any]) -> None:
        pass


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator over Domain spaces — in-tree
    Bayesian-style search with no external dependency (the reference
    delegates to optuna/hyperopt behind the same Searcher interface).

    Per key (independence assumption, as in TPE): observations split
    into the top `gamma` fraction ("good") and the rest; numeric
    domains draw candidates from a Parzen (gaussian-kernel) density
    over good values and keep the candidate maximizing the good/bad
    density ratio; categorical domains sample from smoothed good
    counts. Below `n_initial` observations it falls back to random
    sampling.
    """

    def __init__(self, param_space: Dict[str, Any], metric: str,
                 mode: str = "max", *, n_initial: int = 5,
                 gamma: float = 0.25, n_candidates: int = 24,
                 seed: Optional[int] = None):
        super().__init__(metric, mode)
        if any(isinstance(v, GridSearch) for v in param_space.values()):
            raise ValueError("TPESearcher does not support grid_search "
                             "entries; use BasicVariantGenerator")
        self.param_space = dict(param_space)
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._live: Dict[str, Dict[str, Any]] = {}
        self._obs: List[Any] = []  # (config, score)

    # numeric transform: LogUniform works in log space
    def _to_x(self, key: str, v: float) -> float:
        import math
        return math.log(v) if isinstance(self.param_space[key],
                                         LogUniform) else float(v)

    def _from_x(self, key: str, x: float) -> Any:
        import math
        dom = self.param_space[key]
        if isinstance(dom, LogUniform):
            return float(min(max(math.exp(x), math.exp(dom._lo)),
                             math.exp(dom._hi)))
        if isinstance(dom, RandInt):
            return int(min(max(round(x), dom.low), dom.high - 1))
        return float(min(max(x, dom.low), dom.high))

    def _random_config(self) -> Dict[str, Any]:
        return {k: (v.sample(self._rng) if isinstance(v, Domain) else v)
                for k, v in self.param_space.items()}

    def suggest(self, trial_id: str) -> Dict[str, Any]:
        if len(self._obs) < self.n_initial:
            cfg = self._random_config()
            self._live[trial_id] = cfg
            return dict(cfg)
        sign = 1.0 if self.mode == "max" else -1.0
        ranked = sorted(self._obs, key=lambda o: sign * o[1],
                        reverse=True)
        n_good = max(1, int(len(ranked) * self.gamma))
        good = [o[0] for o in ranked[:n_good]]
        bad = [o[0] for o in ranked[n_good:]] or good
        cfg: Dict[str, Any] = {}
        for k, dom in self.param_space.items():
            if isinstance(dom, Choice):
                counts = {c: 1.0 for c in dom.categories}
                for g in good:
                    counts[g[k]] = counts.get(g[k], 1.0) + 1.0
                total = sum(counts.values())
                r = self._rng.random() * total
                acc = 0.0
                for c, w in counts.items():
                    acc += w
                    if r <= acc:
                        cfg[k] = c
                        break
            elif isinstance(dom, Domain):
                import math
                gx = [self._to_x(k, g[k]) for g in good]
                bx = [self._to_x(k, b[k]) for b in bad]
                spread = (max(gx + bx) - min(gx + bx)) or 1.0
                bw = max(spread / max(3, len(gx)) * 2.0, 1e-6)

                def density(x, pts, bw=bw):
                    return sum(math.exp(-0.5 * ((x - p) / bw) ** 2)
                               for p in pts) / (len(pts) * bw) + 1e-12

                best_x, best_score = None, -1.0
                for _ in range(self.n_candidates):
                    seed_pt = self._rng.choice(gx)
                    x = self._rng.gauss(seed_pt, bw)
                    score = density(x, gx) / density(x, bx)
                    if score > best_score:
                        best_x, best_score = x, score
                cfg[k] = self._from_x(k, best_x)
            else:
                cfg[k] = dom
        self._live[trial_id] = cfg
        return dict(cfg)

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        cfg = self._live.pop(trial_id, None)
        if cfg is None or error or not result or \
                self.metric not in result:
            return
        self._obs.append((cfg, float(result[self.metric])))

    def save(self) -> Dict[str, Any]:
        return {"obs": list(self._obs)}

    def restore(self, state: Dict[str, Any]) -> None:
        self._obs = list(state.get("obs", []))


class OptunaSearcher(Searcher):
    """Adapter for optuna's TPE/CMA samplers behind the same Searcher
    interface (reference tune/search/optuna). Importable without
    optuna; constructing it without the package raises with guidance
    (the interface is the parity surface — environments with optuna
    plug it in unchanged)."""

    def __init__(self, param_space: Dict[str, Any], metric: str,
                 mode: str = "max", **optuna_kwargs: Any):
        super().__init__(metric, mode)
        try:
            import optuna
        except ImportError as e:
            raise ImportError(
                "OptunaSearcher requires the 'optuna' package; use "
                "TPESearcher for the in-tree equivalent") from e
        direction = "maximize" if mode == "max" else "minimize"
        self._study = optuna.create_study(direction=direction,
                                          **optuna_kwargs)
        self.param_space = dict(param_space)
        self._trials: Dict[str, Any] = {}

    def suggest(self, trial_id: str) -> Dict[str, Any]:
        t = self._study.ask()
        cfg: Dict[str, Any] = {}
        for k, dom in self.param_space.items():
            if isinstance(dom, Choice):
                cfg[k] = t.suggest_categorical(k, dom.categories)
            elif isinstance(dom, LogUniform):
                import math
                cfg[k] = t.suggest_float(k, math.exp(dom._lo),
                                         math.exp(dom._hi), log=True)
            elif isinstance(dom, RandInt):
                cfg[k] = t.suggest_int(k, dom.low, dom.high - 1)
            elif isinstance(dom, Uniform):
                cfg[k] = t.suggest_float(k, dom.low, dom.high)
            else:
                cfg[k] = dom
        self._trials[trial_id] = t
        return cfg

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        t = self._trials.pop(trial_id, None)
        if t is None:
            return
        if error or not result or self.metric not in result:
            import optuna
            self._study.tell(t, state=optuna.trial.TrialState.FAIL)
            return
        self._study.tell(t, float(result[self.metric]))
