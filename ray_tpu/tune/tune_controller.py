"""TuneController: drives N trials as actors with retries + scheduling.

reference parity: python/ray/tune/execution/tune_controller.py:73 — the
event loop owning trial actors: start up to max_concurrent, collect
results asynchronously, apply scheduler decisions (ASHA stops), retry
failed trials from their latest checkpoint, persist per-trial state under
the experiment dir (experiment/trial.py:245 Trial contract).
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.tune.schedulers import CONTINUE, FIFOScheduler, STOP

logger = logging.getLogger(__name__)

PENDING, RUNNING, TERMINATED, ERROR = \
    "PENDING", "RUNNING", "TERMINATED", "ERROR"


class _TrialRunner:
    """The per-trial actor: hosts one trainable instance."""

    def __init__(self, factory: Callable[[Dict[str, Any]], Any],
                 config: Dict[str, Any]):
        self._factory = factory
        self._t = factory(config)

    def ping(self) -> str:
        return "pong"

    def reset(self, config: Dict[str, Any],
              checkpoint_dir: Optional[str] = None) -> None:
        """In-place trainable swap (reference reuse_actors /
        Trainable.reset): rebuild with a new config, optionally
        restoring a checkpoint — no actor churn, no scheduling race."""
        try:
            self._t.stop()
        except Exception:  # noqa: BLE001 - old trainable already stopped or broken
            pass
        self._t = self._factory(config)
        if checkpoint_dir:
            self._t.restore(checkpoint_dir)

    def train(self) -> Dict[str, Any]:
        return self._t.train()

    def save(self, checkpoint_dir: str) -> str:
        return self._t.save(checkpoint_dir)

    def save_auto(self, trial_dir: str) -> str:
        """Save under checkpoint_{iteration} named from the trainable's
        OWN iteration at save time. Used when the controller cannot know
        the iteration in advance (a train() is still in flight ahead of
        this call in the actor's queue, so controller-side naming would
        be one iteration behind the contents)."""
        import os
        # Trainable exposes .iteration; RLlib Algorithm keeps _iteration
        it = getattr(self._t, "iteration",
                     getattr(self._t, "_iteration", 0))
        return self._t.save(os.path.join(
            trial_dir, f"checkpoint_{int(it):06d}"))

    def restore(self, checkpoint_dir: str) -> None:
        self._t.restore(checkpoint_dir)

    def stop(self) -> None:
        self._t.stop()


@dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    state: str = PENDING
    actor: Any = None
    in_flight: Any = None           # ObjectRef of the pending train() call
    results: List[Dict[str, Any]] = field(default_factory=list)
    last_result: Dict[str, Any] = field(default_factory=dict)
    checkpoint_dir: Optional[str] = None
    num_failures: int = 0
    num_restores: int = 0
    error: Optional[BaseException] = None
    trial_dir: str = ""

    @property
    def iteration(self) -> int:
        return self.last_result.get("training_iteration", 0)


class TuneController:
    def __init__(self, factory: Callable[[Dict[str, Any]], Any],
                 variants: List[Dict[str, Any]], *,
                 run_dir: str,
                 stop: Optional[Dict[str, Any]] = None,
                 scheduler: Optional[Any] = None,
                 max_concurrent_trials: int = 4,
                 max_failures_per_trial: int = 1,
                 checkpoint_frequency: int = 0,
                 resources_per_trial: Optional[Dict[str, float]] = None,
                 resume_state: Optional[Dict[str, Any]] = None,
                 searcher: Optional[Any] = None,
                 num_searcher_trials: int = 0):
        self._factory = factory
        self._stop = dict(stop or {})
        self._scheduler = scheduler or FIFOScheduler()
        # sequential search algorithm (reference search_alg): suggests
        # one config per new trial, fed completed results
        self._searcher = searcher
        self._num_searcher_trials = num_searcher_trials
        self._max_concurrent = max_concurrent_trials
        self._max_failures = max_failures_per_trial
        self._ckpt_freq = checkpoint_frequency
        self._resources = dict(resources_per_trial or {"CPU": 1})
        self.run_dir = run_dir
        self.trials = [
            Trial(trial_id=f"trial_{i:05d}", config=cfg,
                  trial_dir=os.path.join(run_dir, f"trial_{i:05d}"))
            for i, cfg in enumerate(variants)
        ]
        for t in self.trials:
            os.makedirs(t.trial_dir, exist_ok=True)
        if resume_state:
            self._apply_resume_state(resume_state)
        # PBT-style schedulers track every trial's config for exploit
        if hasattr(self._scheduler, "on_trial_add"):
            for t in self.trials:
                self._scheduler.on_trial_add(t.trial_id, t.config)

    # -- experiment state (Tuner.restore; reference
    # tune/execution/experiment_state.py) ----------------------------------

    def _apply_resume_state(self, state: Dict[str, Any]) -> None:
        """Rehydrate trials: finished ones keep their results; errored /
        interrupted ones go back to PENDING and resume from their latest
        checkpoint when one exists."""
        by_id = {t["trial_id"]: t for t in state.get("trials", [])}
        for t in self.trials:
            saved = by_id.get(t.trial_id)
            if not saved:
                continue
            t.config = saved["config"]
            t.results = list(saved["results"])
            t.last_result = dict(saved["last_result"])
            t.checkpoint_dir = saved["checkpoint_dir"]
            t.num_restores = saved.get("num_restores", 0)
            t.state = (TERMINATED if saved["state"] == TERMINATED
                       else PENDING)
            if t.state == PENDING:
                # the rerun replays iterations after the checkpoint
                # (or from scratch): drop recorded results past that
                # point so training_iteration stays unique in results
                ckpt_iter = 0
                if t.checkpoint_dir:
                    tail = os.path.basename(t.checkpoint_dir)
                    if tail.startswith("checkpoint_"):
                        try:
                            ckpt_iter = int(tail.split("_")[-1])
                        except ValueError:
                            ckpt_iter = 0
                t.results = [
                    r for r in t.results
                    if r.get("training_iteration", 0) <= ckpt_iter]
                t.last_result = dict(t.results[-1]) if t.results else {}

    def experiment_state(self) -> Dict[str, Any]:
        return {"trials": [
            {"trial_id": t.trial_id, "config": t.config,
             "state": t.state, "results": t.results,
             "last_result": t.last_result,
             "checkpoint_dir": t.checkpoint_dir,
             "num_restores": t.num_restores,
             "error": repr(t.error) if t.error else None}
            for t in self.trials]}

    def _save_experiment_state(self) -> None:
        import pickle
        tmp = os.path.join(self.run_dir, ".experiment_state.tmp")
        with open(tmp, "wb") as f:
            pickle.dump(self.experiment_state(), f)
        os.replace(tmp,
                   os.path.join(self.run_dir, "experiment_state.pkl"))

    # -- actor lifecycle ---------------------------------------------------

    def _start_trial(self, trial: Trial, restore: bool = False) -> None:
        runner_cls = ray_tpu.remote(_TrialRunner)
        trial.actor = runner_cls.options(**_resource_options(
            self._resources)).remote(self._factory, trial.config)
        if restore and trial.checkpoint_dir:
            ray_tpu.get(trial.actor.restore.remote(trial.checkpoint_dir),
                        timeout=300)
            trial.num_restores += 1
        trial.state = RUNNING
        trial.in_flight = trial.actor.train.remote()

    def _stop_trial(self, trial: Trial, state: str,
                    save_final: bool = True) -> None:
        if trial.actor is not None:
            try:
                if save_final and state == TERMINATED:
                    trial.checkpoint_dir = ray_tpu.get(
                        trial.actor.save.remote(self._next_ckpt_dir(trial)),
                        timeout=300)
                ray_tpu.get(trial.actor.stop.remote(), timeout=60)
            except Exception:  # noqa: BLE001 - wedged/dead; kill below is the backstop
                pass
            try:
                ray_tpu.kill(trial.actor)
            except Exception:  # noqa: BLE001 - actor already dead
                pass
        trial.actor = None
        trial.in_flight = None
        trial.state = state
        if state in (TERMINATED, ERROR):
            self._notify_searcher(trial)
            if hasattr(self._scheduler, "on_trial_remove"):
                self._scheduler.on_trial_remove(trial.trial_id)

    def _next_ckpt_dir(self, trial: Trial) -> str:
        return os.path.join(trial.trial_dir,
                            f"checkpoint_{trial.iteration:06d}")

    # -- stop conditions ---------------------------------------------------

    def _should_stop(self, result: Dict[str, Any]) -> bool:
        if result.get("done"):
            return True
        for key, bound in self._stop.items():
            if key in result and result[key] >= bound:
                return True
        return False

    # -- the loop ----------------------------------------------------------

    def _maybe_suggest_trials(self) -> None:
        """Create new trials from the searcher up to the concurrency
        cap, until its trial budget is spent (reference: SearchGenerator
        feeding TuneController)."""
        if self._searcher is None:
            return
        active = [t for t in self.trials
                  if t.state in (PENDING, RUNNING)]
        while len(self.trials) < self._num_searcher_trials and \
                len(active) < self._max_concurrent:
            trial_id = f"trial_{len(self.trials):05d}"
            cfg = self._searcher.suggest(trial_id)
            if cfg is None:
                return
            t = Trial(trial_id=trial_id, config=cfg,
                      trial_dir=os.path.join(self.run_dir, trial_id))
            os.makedirs(t.trial_dir, exist_ok=True)
            self.trials.append(t)
            active.append(t)
            if hasattr(self._scheduler, "on_trial_add"):
                self._scheduler.on_trial_add(t.trial_id, t.config)

    def _notify_searcher(self, trial: Trial) -> None:
        if self._searcher is None or \
                getattr(trial, "_searcher_notified", False):
            return
        trial._searcher_notified = True  # type: ignore[attr-defined]
        try:
            self._searcher.on_trial_complete(
                trial.trial_id, trial.last_result or None,
                error=trial.state == ERROR)
        except Exception:  # noqa: BLE001
            logger.warning("searcher on_trial_complete failed",
                           exc_info=True)

    def run(self, timeout_s: float = 3600.0) -> List[Trial]:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self._maybe_suggest_trials()
            # launch pending trials up to the concurrency cap
            running = [t for t in self.trials if t.state == RUNNING]
            pending = [t for t in self.trials if t.state == PENDING]
            for t in pending[:max(0, self._max_concurrent - len(running))]:
                try:
                    # resumed trials restart from their checkpoint
                    self._start_trial(t, restore=bool(t.checkpoint_dir))
                except Exception as e:  # noqa: BLE001
                    t.error = e
                    t.state = ERROR
                    self._notify_searcher(t)
            running = [t for t in self.trials if t.state == RUNNING]
            if not running:
                break
            refs = [t.in_flight for t in running]
            ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=5.0)
            if ready:
                # drain everything already finished, not just the first
                # listed trial — handling one ref per pass starves later
                # trials whenever an earlier one always has results ready
                # (schedulers then never see the starved trials' scores)
                ready, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                        timeout=0)
            for ref in ready:
                trial = next(t for t in running if t.in_flight == ref)
                if trial.state == RUNNING and trial.in_flight == ref:
                    self._handle_ready(trial, ref)
        # Time budget expired: don't leak live actors (they'd keep holding
        # resources and training forever).
        for t in self.trials:
            if t.state == RUNNING:
                t.error = TimeoutError(
                    "tune run hit its time budget with this trial running")
                self._stop_trial(t, ERROR, save_final=False)
        self._save_experiment_state()
        return self.trials

    def _handle_ready(self, trial: Trial, ref: Any) -> None:
        try:
            result = ray_tpu.get(ref)
        except Exception as e:  # noqa: BLE001
            self._handle_trial_failure(trial, e)
            return
        result.setdefault("trial_id", trial.trial_id)
        trial.results.append(result)
        trial.last_result = result
        # persist after every result: restore-after-hard-kill must see
        # progress, not just the state at the last trial stop
        self._save_experiment_state()
        if self._ckpt_freq and trial.iteration % self._ckpt_freq == 0:
            try:
                trial.checkpoint_dir = ray_tpu.get(
                    trial.actor.save.remote(self._next_ckpt_dir(trial)),
                    timeout=300)
            except Exception:  # noqa: BLE001
                logger.warning("periodic checkpoint failed for %s",
                               trial.trial_id, exc_info=True)
        if self._should_stop(result):
            self._stop_trial(trial, TERMINATED)
            self._save_experiment_state()
            return
        decision = self._scheduler.on_result(trial.trial_id, result)
        if decision == STOP:
            logger.info("scheduler stopped %s at iter %d",
                        trial.trial_id, trial.iteration)
            self._stop_trial(trial, TERMINATED)
            self._save_experiment_state()
            return
        if isinstance(decision, dict) and \
                decision.get("action") == "exploit":
            self._exploit(trial, decision)
            self._save_experiment_state()
            return
        assert decision == CONTINUE
        trial.in_flight = trial.actor.train.remote()

    def _exploit(self, trial: Trial, decision: Dict[str, Any]) -> None:
        """PBT exploit: snapshot the source trial, then restart this
        trial from that checkpoint with the explored config (reference
        pbt.py _exploit + tune_controller trial restore path)."""
        src = next(t for t in self.trials
                   if t.trial_id == decision["source"])
        try:
            if src.state == RUNNING and src.actor is not None:
                # actor calls are ordered: save runs after the source's
                # in-flight train() completes, so the actor (not the
                # controller) must pick the checkpoint_{iteration} name
                src.checkpoint_dir = ray_tpu.get(
                    src.actor.save_auto.remote(src.trial_dir),
                    timeout=300)
        except Exception:  # noqa: BLE001
            logger.warning("PBT source snapshot failed for %s",
                           src.trial_id, exc_info=True)
        if not src.checkpoint_dir:
            # no checkpoint to exploit — keep training as-is
            trial.in_flight = trial.actor.train.remote()
            return
        logger.info("PBT: %s exploits %s (new config %s)",
                    trial.trial_id, src.trial_id, decision["config"])
        trial.config = dict(decision["config"])
        trial.checkpoint_dir = src.checkpoint_dir
        try:
            # in-place reset on the same actor (reference reuse_actors)
            ray_tpu.get(trial.actor.reset.remote(
                trial.config, trial.checkpoint_dir), timeout=300)
            trial.num_restores += 1
            if hasattr(self._scheduler, "confirm_exploit"):
                self._scheduler.confirm_exploit(trial.trial_id,
                                                trial.config)
            trial.in_flight = trial.actor.train.remote()
        except Exception as e:  # noqa: BLE001
            trial.error = e
            self._stop_trial(trial, ERROR, save_final=False)

    def _handle_trial_failure(self, trial: Trial,
                              error: BaseException) -> None:
        trial.num_failures += 1
        if trial.num_failures > self._max_failures:
            trial.error = error
            self._stop_trial(trial, ERROR, save_final=False)
            return
        logger.warning(
            "trial %s failed (%d/%d): %r — restarting from %s",
            trial.trial_id, trial.num_failures, self._max_failures, error,
            trial.checkpoint_dir or "scratch")
        try:
            ray_tpu.kill(trial.actor)
        except Exception:  # noqa: BLE001 - actor already dead
            pass
        try:
            self._start_trial(trial, restore=True)
        except Exception as e:  # noqa: BLE001
            trial.error = e
            self._stop_trial(trial, ERROR, save_final=False)


def _resource_options(resources: Dict[str, float]) -> Dict[str, Any]:
    opts: Dict[str, Any] = {}
    res = dict(resources)
    if "CPU" in res:
        opts["num_cpus"] = res.pop("CPU")
    if "TPU" in res:
        opts["num_tpus"] = res.pop("TPU")
    if res:
        opts["resources"] = res
    return opts
