"""TuneController: drives N trials as actors with retries + scheduling.

reference parity: python/ray/tune/execution/tune_controller.py:73 — the
event loop owning trial actors: start up to max_concurrent, collect
results asynchronously, apply scheduler decisions (ASHA stops), retry
failed trials from their latest checkpoint, persist per-trial state under
the experiment dir (experiment/trial.py:245 Trial contract).
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.tune.schedulers import CONTINUE, FIFOScheduler, STOP

logger = logging.getLogger(__name__)

PENDING, RUNNING, TERMINATED, ERROR = \
    "PENDING", "RUNNING", "TERMINATED", "ERROR"


class _TrialRunner:
    """The per-trial actor: hosts one trainable instance."""

    def __init__(self, factory: Callable[[Dict[str, Any]], Any],
                 config: Dict[str, Any]):
        self._t = factory(config)

    def ping(self) -> str:
        return "pong"

    def train(self) -> Dict[str, Any]:
        return self._t.train()

    def save(self, checkpoint_dir: str) -> str:
        return self._t.save(checkpoint_dir)

    def restore(self, checkpoint_dir: str) -> None:
        self._t.restore(checkpoint_dir)

    def stop(self) -> None:
        self._t.stop()


@dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    state: str = PENDING
    actor: Any = None
    in_flight: Any = None           # ObjectRef of the pending train() call
    results: List[Dict[str, Any]] = field(default_factory=list)
    last_result: Dict[str, Any] = field(default_factory=dict)
    checkpoint_dir: Optional[str] = None
    num_failures: int = 0
    num_restores: int = 0
    error: Optional[BaseException] = None
    trial_dir: str = ""

    @property
    def iteration(self) -> int:
        return self.last_result.get("training_iteration", 0)


class TuneController:
    def __init__(self, factory: Callable[[Dict[str, Any]], Any],
                 variants: List[Dict[str, Any]], *,
                 run_dir: str,
                 stop: Optional[Dict[str, Any]] = None,
                 scheduler: Optional[Any] = None,
                 max_concurrent_trials: int = 4,
                 max_failures_per_trial: int = 1,
                 checkpoint_frequency: int = 0,
                 resources_per_trial: Optional[Dict[str, float]] = None):
        self._factory = factory
        self._stop = dict(stop or {})
        self._scheduler = scheduler or FIFOScheduler()
        self._max_concurrent = max_concurrent_trials
        self._max_failures = max_failures_per_trial
        self._ckpt_freq = checkpoint_frequency
        self._resources = dict(resources_per_trial or {"CPU": 1})
        self.run_dir = run_dir
        self.trials = [
            Trial(trial_id=f"trial_{i:05d}", config=cfg,
                  trial_dir=os.path.join(run_dir, f"trial_{i:05d}"))
            for i, cfg in enumerate(variants)
        ]
        for t in self.trials:
            os.makedirs(t.trial_dir, exist_ok=True)

    # -- actor lifecycle ---------------------------------------------------

    def _start_trial(self, trial: Trial, restore: bool = False) -> None:
        runner_cls = ray_tpu.remote(_TrialRunner)
        trial.actor = runner_cls.options(**_resource_options(
            self._resources)).remote(self._factory, trial.config)
        if restore and trial.checkpoint_dir:
            ray_tpu.get(trial.actor.restore.remote(trial.checkpoint_dir),
                        timeout=300)
            trial.num_restores += 1
        trial.state = RUNNING
        trial.in_flight = trial.actor.train.remote()

    def _stop_trial(self, trial: Trial, state: str,
                    save_final: bool = True) -> None:
        if trial.actor is not None:
            try:
                if save_final and state == TERMINATED:
                    trial.checkpoint_dir = ray_tpu.get(
                        trial.actor.save.remote(self._next_ckpt_dir(trial)),
                        timeout=300)
                ray_tpu.get(trial.actor.stop.remote(), timeout=60)
            except Exception:  # noqa: BLE001
                pass
            try:
                ray_tpu.kill(trial.actor)
            except Exception:  # noqa: BLE001
                pass
        trial.actor = None
        trial.in_flight = None
        trial.state = state

    def _next_ckpt_dir(self, trial: Trial) -> str:
        return os.path.join(trial.trial_dir,
                            f"checkpoint_{trial.iteration:06d}")

    # -- stop conditions ---------------------------------------------------

    def _should_stop(self, result: Dict[str, Any]) -> bool:
        if result.get("done"):
            return True
        for key, bound in self._stop.items():
            if key in result and result[key] >= bound:
                return True
        return False

    # -- the loop ----------------------------------------------------------

    def run(self, timeout_s: float = 3600.0) -> List[Trial]:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            # launch pending trials up to the concurrency cap
            running = [t for t in self.trials if t.state == RUNNING]
            pending = [t for t in self.trials if t.state == PENDING]
            for t in pending[:max(0, self._max_concurrent - len(running))]:
                try:
                    self._start_trial(t)
                except Exception as e:  # noqa: BLE001
                    t.error = e
                    t.state = ERROR
            running = [t for t in self.trials if t.state == RUNNING]
            if not running:
                break
            refs = [t.in_flight for t in running]
            ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=5.0)
            for ref in ready:
                trial = next(t for t in running if t.in_flight == ref)
                self._handle_ready(trial, ref)
        # Time budget expired: don't leak live actors (they'd keep holding
        # resources and training forever).
        for t in self.trials:
            if t.state == RUNNING:
                t.error = TimeoutError(
                    "tune run hit its time budget with this trial running")
                self._stop_trial(t, ERROR, save_final=False)
        return self.trials

    def _handle_ready(self, trial: Trial, ref: Any) -> None:
        try:
            result = ray_tpu.get(ref)
        except Exception as e:  # noqa: BLE001
            self._handle_trial_failure(trial, e)
            return
        result.setdefault("trial_id", trial.trial_id)
        trial.results.append(result)
        trial.last_result = result
        if self._ckpt_freq and trial.iteration % self._ckpt_freq == 0:
            try:
                trial.checkpoint_dir = ray_tpu.get(
                    trial.actor.save.remote(self._next_ckpt_dir(trial)),
                    timeout=300)
            except Exception:  # noqa: BLE001
                logger.warning("periodic checkpoint failed for %s",
                               trial.trial_id, exc_info=True)
        if self._should_stop(result):
            self._stop_trial(trial, TERMINATED)
            return
        decision = self._scheduler.on_result(trial.trial_id, result)
        if decision == STOP:
            logger.info("scheduler stopped %s at iter %d",
                        trial.trial_id, trial.iteration)
            self._stop_trial(trial, TERMINATED)
            return
        assert decision == CONTINUE
        trial.in_flight = trial.actor.train.remote()

    def _handle_trial_failure(self, trial: Trial,
                              error: BaseException) -> None:
        trial.num_failures += 1
        if trial.num_failures > self._max_failures:
            trial.error = error
            self._stop_trial(trial, ERROR, save_final=False)
            return
        logger.warning(
            "trial %s failed (%d/%d): %r — restarting from %s",
            trial.trial_id, trial.num_failures, self._max_failures, error,
            trial.checkpoint_dir or "scratch")
        try:
            ray_tpu.kill(trial.actor)
        except Exception:  # noqa: BLE001
            pass
        try:
            self._start_trial(trial, restore=True)
        except Exception as e:  # noqa: BLE001
            trial.error = e
            self._stop_trial(trial, ERROR, save_final=False)


def _resource_options(resources: Dict[str, float]) -> Dict[str, Any]:
    opts: Dict[str, Any] = {}
    res = dict(resources)
    if "CPU" in res:
        opts["num_cpus"] = res.pop("CPU")
    if "TPU" in res:
        opts["num_tpus"] = res.pop("TPU")
    if res:
        opts["resources"] = res
    return opts
