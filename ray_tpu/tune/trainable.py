"""Trainable contract + function-trainable wrapper.

reference parity: python/ray/tune/trainable/trainable.py (the
step/save/restore contract used by TuneController, experiment/trial.py:245)
and trainable/function_trainable.py (function API with tune.report).
RLlib's Algorithm satisfies this contract natively (train/save/restore),
as does any user subclass of Trainable.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, Optional


class Trainable:
    """Subclass API: override setup/step/save_checkpoint/load_checkpoint."""

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        self.config = dict(config or {})
        self.iteration = 0
        self.setup(self.config)

    # -- override points ----------------------------------------------
    def setup(self, config: Dict[str, Any]) -> None:
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> None:
        pass

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        pass

    def cleanup(self) -> None:
        pass

    # -- controller-facing contract (matches Algorithm.train/save/...) ----
    def train(self) -> Dict[str, Any]:
        result = self.step()
        self.iteration += 1
        result.setdefault("training_iteration", self.iteration)
        return result

    def save(self, checkpoint_dir: str) -> str:
        import json
        import os
        os.makedirs(checkpoint_dir, exist_ok=True)
        self.save_checkpoint(checkpoint_dir)
        # Persist the iteration counter so a restored trial's
        # training_iteration (and therefore stop conditions) continues
        # where it left off (reference trainable saves .tune_metadata).
        with open(os.path.join(checkpoint_dir, ".tune_metadata"), "w") as f:
            json.dump({"iteration": self.iteration}, f)
        return checkpoint_dir

    def restore(self, checkpoint_dir: str) -> None:
        import json
        import os
        meta_path = os.path.join(checkpoint_dir, ".tune_metadata")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                self.iteration = json.load(f)["iteration"]
        self.load_checkpoint(checkpoint_dir)

    def stop(self) -> None:
        self.cleanup()


class _FunctionSession:
    """Bridges tune.report() inside a user function to the trial actor."""

    def __init__(self) -> None:
        self.results: "queue.Queue" = queue.Queue(maxsize=1)

    def report(self, metrics: Dict[str, Any]) -> None:
        self.results.put(("result", dict(metrics)))


_fn_session: Optional[_FunctionSession] = None


def _get_fn_session() -> Optional[_FunctionSession]:
    return _fn_session


class FunctionTrainable(Trainable):
    """Wraps fn(config) calling tune.report(...) per iteration; each
    train() returns the next reported result (reference
    function_trainable.py's result queue handshake)."""

    _fn: Callable[[Dict[str, Any]], Any] = None  # set by subclassing factory

    def setup(self, config: Dict[str, Any]) -> None:
        global _fn_session
        self._session = _FunctionSession()
        _fn_session = self._session
        self._done = False

        def runner() -> None:
            try:
                out = type(self)._fn(config)
                if isinstance(out, dict):
                    # returning a metrics dict is a final report
                    # (reference function trainables support both
                    # tune.report(...) and a returned dict)
                    self._session.results.put(("result", dict(out)))
                self._session.results.put(("done", {}))
            except BaseException as e:  # noqa: BLE001
                self._session.results.put(("error", e))

        self._thread = threading.Thread(target=runner, daemon=True,
                                        name="tune-fn")
        self._thread.start()

    def step(self) -> Dict[str, Any]:
        if self._done:
            return {"done": True}
        kind, payload = self._session.results.get()
        if kind == "error":
            raise payload
        if kind == "done":
            self._done = True
            return {"done": True}
        payload.setdefault("done", False)
        return payload

    def restore(self, checkpoint_dir: str) -> None:
        # A function trainable replays fn(config) from its beginning on
        # restart — resuming the iteration counter from .tune_metadata
        # would mislabel the replayed reports and truncate the run against
        # iteration-based stop conditions. Restarts are from scratch.
        self.iteration = 0


def wrap_function(fn: Callable[[Dict[str, Any]], Any]) -> type:
    return type(f"fn_{getattr(fn, '__name__', 'trainable')}",
                (FunctionTrainable,), {"_fn": staticmethod(fn)})


def report(metrics: Optional[Dict[str, Any]] = None, **kwargs: Any) -> None:
    """tune.report inside a function trainable."""
    s = _get_fn_session()
    if s is None:
        raise RuntimeError("tune.report() called outside a tune function "
                           "trainable")
    merged = dict(metrics or {})
    merged.update(kwargs)
    s.report(merged)
