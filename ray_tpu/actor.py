"""ActorClass / ActorHandle / ActorMethod.

reference parity: python/ray/actor.py — ActorClass (:425), ActorClass._remote
(:708), ActorHandle (:1067), ActorMethod (:107). Actor-only options per
_private/ray_option_utils.py: max_restarts, max_task_retries,
max_concurrency, lifetime, name, namespace, get_if_exists, max_pending_calls,
concurrency_groups.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.ids import ActorID, TaskID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.state import (DefaultSchedulingStrategy, TaskSpec,
                                    TaskType)
from ray_tpu.remote_function import (build_resources, pack_args,
                                     validate_runtime_env, _extract_pg)

_ACTOR_OPTIONS = {
    "num_cpus", "num_gpus", "num_tpus", "resources", "memory", "name",
    "namespace", "lifetime", "max_restarts", "max_task_retries",
    "max_concurrency", "max_pending_calls", "get_if_exists",
    "scheduling_strategy", "runtime_env", "accelerator_type",
    "placement_group", "placement_group_bundle_index",
    "placement_group_capture_child_tasks", "object_store_memory",
    "concurrency_groups", "_metadata",
}


def method(**options: Any):
    """@ray_tpu.method decorator: per-method defaults on actor classes
    (reference ray.method — num_returns, concurrency_group)."""
    allowed = {"num_returns", "concurrency_group"}
    bad = set(options) - allowed
    if bad:
        raise ValueError(f"invalid method options: {sorted(bad)}")

    def deco(fn):
        fn.__ray_tpu_method_options__ = options
        return fn

    return deco


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: int = 1, concurrency_group: str = ""):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group

    def options(self, **kwargs: Any) -> "ActorMethod":
        return ActorMethod(
            self._handle, self._method_name,
            kwargs.get("num_returns", self._num_returns),
            kwargs.get("concurrency_group", self._concurrency_group))

    def remote(self, *args: Any, **kwargs: Any) -> Any:
        return self._handle._submit(self._method_name, args, kwargs,
                                    self._num_returns,
                                    self._concurrency_group)

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        raise TypeError(
            f"actor method '{self._method_name}' cannot be called directly; "
            f"use .remote()")


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str,
                 method_names: List[str], fn_key: str,
                 method_options: Optional[Dict[str, Dict[str, Any]]]
                 = None,
                 concurrency_groups: Optional[List[str]] = None,
                 max_pending_calls: int = -1):
        self._actor_id = actor_id
        self._class_name = class_name
        self._method_names = list(method_names)
        self._fn_key = fn_key
        self._method_options = dict(method_options or {})
        self._concurrency_groups = list(concurrency_groups or [])
        self._max_pending_calls = int(max_pending_calls)
        w = worker_mod.global_worker_or_none()
        if w is not None:
            w.core_worker.attach_actor(actor_id)

    @property
    def _actor_id_hex(self) -> str:
        return self._actor_id.hex()

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self._method_names:
            raise AttributeError(
                f"actor {self._class_name} has no method '{name}'")
        opts = self._method_options.get(name, {})
        method = ActorMethod(self, name,
                             opts.get("num_returns", 1),
                             opts.get("concurrency_group", ""))
        # cache on the instance: __getattr__ only fires on misses, so
        # `handle.m.remote()` in a hot loop builds the method once
        self.__dict__[name] = method
        return method

    def _submit(self, method_name: str, args: tuple, kwargs: dict,
                num_returns: int, concurrency_group: str = "") -> Any:
        if concurrency_group and \
                concurrency_group not in self._concurrency_groups:
            # reference raises too — a silent default-pool fallback
            # would lose the isolation the caller asked for
            raise ValueError(
                f"actor {self._class_name} has no concurrency group "
                f"{concurrency_group!r}; declared: "
                f"{self._concurrency_groups}")
        w = worker_mod.global_worker()
        args_blob, arg_refs = pack_args(args, kwargs)
        # generator actor methods (reference StreamingObjectRefGenerator
        # works for actor tasks too, _raylet.pyx:269)
        dynamic = num_returns in ("dynamic", "streaming")
        refs = w.core_worker.submit_actor_task(
            self._actor_id, method_name, self._fn_key, args_blob, arg_refs,
            1 if dynamic else num_returns,
            concurrency_group=concurrency_group,
            max_pending_calls=self._max_pending_calls,
            dynamic_returns=dynamic)
        if dynamic and num_returns == "streaming":
            from ray_tpu._private.object_ref import ObjectRefGenerator
            return ObjectRefGenerator(refs[0])
        if dynamic or num_returns == 1:
            return refs[0]
        return refs

    def __repr__(self) -> str:
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name,
                              self._method_names, self._fn_key,
                              self._method_options,
                              self._concurrency_groups,
                              self._max_pending_calls))


class ActorClass:
    def __init__(self, cls: type, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = dict(options or {})
        bad = set(self._options) - _ACTOR_OPTIONS
        if bad:
            raise ValueError(f"invalid actor options: {sorted(bad)}")
        self._fn_key: Optional[str] = None
        self._client_ac = None  # cached thin-client wrapper (ray:// mode)

    def options(self, **kwargs: Any) -> "ActorClass":
        ac = ActorClass(self._cls, {**self._options, **kwargs})
        ac._fn_key = self._fn_key
        return ac

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        raise TypeError(
            f"actor class '{self._cls.__name__}' cannot be instantiated "
            f"directly; use .remote()")

    def _method_names(self) -> List[str]:
        return [m for m in dir(self._cls)
                if not m.startswith("_") and callable(getattr(self._cls, m))]

    def _method_options(self) -> Dict[str, Dict[str, Any]]:
        """@ray_tpu.method(...) tags per method name."""
        out: Dict[str, Dict[str, Any]] = {}
        for m in self._method_names():
            tags = getattr(getattr(self._cls, m),
                           "__ray_tpu_method_options__", None)
            if tags:
                out[m] = dict(tags)
        return out

    def _concurrency_groups(self, method_opts: Dict[str, Dict[str, Any]]
                            ) -> Optional[Dict[str, int]]:
        groups = self._options.get("concurrency_groups")
        if groups is not None and (
                not isinstance(groups, dict)
                or not all(isinstance(k, str) and k
                           for k in groups)
                or not all(isinstance(v, int) and v >= 1
                           for v in groups.values())):
            raise ValueError(
                "concurrency_groups must be {non-empty group name: "
                "max_concurrency >= 1}, got " + repr(groups))
        # every method-tagged group must be declared
        declared = set(groups or {})
        for m, tags in method_opts.items():
            g = tags.get("concurrency_group")
            if g and g not in declared:
                raise ValueError(
                    f"method {m!r} uses undeclared concurrency group "
                    f"{g!r}; declare it in "
                    f"options(concurrency_groups={{...}})")
        return dict(groups) if groups else None

    def bind(self, *args: Any, **kwargs: Any):
        """Lazy graph node (reference dag/class_node.py)."""
        from ray_tpu.dag import ClassNode
        return ClassNode(self, args, kwargs)

    def _default_concurrency(self) -> int:
        """Async actors (any `async def` method) default to concurrent
        execution so await-a-later-call patterns work out of the box
        (reference: asyncio actors default max_concurrency=1000;
        capped lower here because each in-flight call holds an exec
        thread while its coroutine runs on the shared loop)."""
        import inspect
        for _, m in inspect.getmembers(
                self._cls, inspect.iscoroutinefunction):
            return 100
        return 1

    def remote(self, *args: Any, **kwargs: Any) -> ActorHandle:
        ctx = worker_mod.client_context()
        if ctx is not None:
            # thin-client session: proxy actor creation (call-time mode
            # resolution; see RemoteFunction.remote); cached so the class
            # ships once
            if self._client_ac is None or self._client_ac._ctx is not ctx:
                self._client_ac = ctx.remote(self._cls, **self._options)
            return self._client_ac.remote(*args, **kwargs)
        w = worker_mod.global_worker()
        cw = w.core_worker
        opts = self._options
        name = opts.get("name") or ""
        namespace = opts.get("namespace") or w.namespace
        method_opts = self._method_options()
        groups = self._concurrency_groups(method_opts)
        group_names = sorted(groups or {})

        if name and opts.get("get_if_exists"):
            info = cw._gcs.call("get_named_actor", name=name,
                                namespace=namespace)
            if info is not None and info.state != "DEAD":
                if self._fn_key is None:
                    self._fn_key = cw.export_function(self._cls)
                return ActorHandle(
                    info.actor_id, self._cls.__name__,
                    self._method_names(), self._fn_key,
                    method_opts, group_names,
                    int(opts.get("max_pending_calls", -1)))

        if self._fn_key is None:
            self._fn_key = cw.export_function(self._cls)
        actor_id = ActorID.of(cw.job_id)
        args_blob, arg_refs = pack_args(args, kwargs)
        strategy = opts.get("scheduling_strategy") or \
            DefaultSchedulingStrategy()
        pg_id, bundle_idx = _extract_pg(opts, strategy)
        lifetime = opts.get("lifetime")
        max_restarts = int(opts.get("max_restarts", 0))
        spec = TaskSpec(
            task_id=TaskID.for_actor_creation(actor_id), job_id=cw.job_id,
            task_type=TaskType.ACTOR_CREATION_TASK,
            function_key=self._fn_key, function_name=self._cls.__name__,
            args=args_blob, arg_object_refs=arg_refs, num_returns=0,
            # reference semantics: actors default to 0 CPU for their
            # lifetime (ray_option_utils: num_cpus default 1 for creation,
            # 0 held) — we hold what's requested, defaulting to 0.
            resources=build_resources(opts, default_num_cpus=0.0),
            owner_address=cw.address, owner_worker_id=cw.worker_id,
            actor_id=actor_id, max_restarts=max_restarts,
            max_task_retries=int(opts.get("max_task_retries", 0)),
            max_concurrency=int(opts.get("max_concurrency",
                                         self._default_concurrency())),
            concurrency_groups=groups,
            scheduling_strategy=strategy, placement_group_id=pg_id,
            placement_group_bundle_index=bundle_idx,
            runtime_env=validate_runtime_env(opts.get("runtime_env")),
            name=name, namespace=namespace,
            detached=(lifetime == "detached"))
        import pickle
        cw._gcs.call("kv_put", key=f"__actor_spec_meta:{actor_id.hex()}",
                     value=pickle.dumps((self._fn_key, self._method_names(),
                                         method_opts, group_names,
                                         int(opts.get("max_pending_calls",
                                                      -1)))))
        try:
            cw.create_actor(spec, name=name, namespace=namespace)
        except Exception as e:  # noqa: BLE001
            # Reclaim the spec metadata written above — but ONLY when
            # the GCS confirms it never registered this actor. A lost
            # RPC response can raise client-side after a server-side
            # success; deleting the meta then would orphan a LIVE actor
            # (get_actor() needs it forever after).
            try:
                reg = cw._gcs.call("get_actor_info",
                                   actor_id_hex=actor_id.hex())
                if reg is None:
                    cw._gcs.call(
                        "kv_del",
                        key=f"__actor_spec_meta:{actor_id.hex()}")
            except Exception:  # noqa: BLE001
                pass  # unreachable GCS: leave the meta in place
            # get_if_exists race: two creators checked the directory,
            # found nothing, and both registered — the loser must fall
            # back to the winner's actor, not error (reference
            # get_if_exists semantics; surfaced by the seeded-chaos
            # interleaving sweep, tests/test_fault_tolerance.py)
            if name and opts.get("get_if_exists"):
                info = cw._gcs.call("get_named_actor", name=name,
                                    namespace=namespace)
                if info is not None and info.state != "DEAD":
                    return ActorHandle(
                        info.actor_id, self._cls.__name__,
                        self._method_names(), self._fn_key,
                        method_opts, group_names,
                        int(opts.get("max_pending_calls", -1)))
            raise
        return ActorHandle(actor_id, self._cls.__name__,
                           self._method_names(), self._fn_key,
                           method_opts, group_names,
                           int(opts.get("max_pending_calls", -1)))


def get_actor(name: str, namespace: str = "") -> ActorHandle:
    """Look up a named actor (reference ray.get_actor)."""
    ctx = worker_mod.client_context()
    if ctx is not None:
        return ctx.get_actor(name, namespace=namespace)
    w = worker_mod.global_worker()
    info = w.core_worker._gcs.call("get_named_actor", name=name,
                                   namespace=namespace or w.namespace)
    if info is None or info.state == "DEAD":
        raise ValueError(f"no live actor named '{name}'")
    fn_key, methods, method_opts, group_names, max_pending = \
        _actor_class_meta(w, info.actor_id.hex())
    return ActorHandle(info.actor_id, info.class_name, methods, fn_key,
                       method_opts, group_names, max_pending)


def _actor_class_meta(w: Any, actor_id_hex: str):
    """Fetch the actor's exported class key + method metadata via GCS."""
    spec: TaskSpec = w.core_worker._gcs.call(
        "kv_get", key=f"__actor_spec_meta:{actor_id_hex}")
    if spec is None:
        raise ValueError(f"actor {actor_id_hex[:12]} metadata missing")
    import pickle
    meta = pickle.loads(spec)
    if len(meta) == 2:  # pre-concurrency-group metadata
        fn_key, methods = meta
        return fn_key, methods, {}, [], -1
    if len(meta) == 4:  # pre-max_pending_calls metadata
        return (*meta, -1)
    return meta
