"""`python -m ray_tpu <command>` → the cluster CLI."""

import sys

from ray_tpu.scripts.cli import main

sys.exit(main())
