"""HTTP ingress for serve deployments.

reference parity: serve/_private/proxy.py:122 (per-node HTTP proxy
routing requests into deployment handles). POST/GET /<deployment-name>
with a JSON body; the body (an object → kwargs, anything else → single
positional arg) is passed to the deployment and the JSON result returned.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict


class HTTPProxyActor:
    def __init__(self, port: int = 8000):
        from ray_tpu.serve.api import DeploymentHandle

        self._handles: Dict[str, Any] = {}
        self._handles_lock = threading.Lock()
        proxy = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def _handle(self, body: Any) -> None:
                import ray_tpu
                name = self.path.strip("/").split("/")[0]
                if not name:
                    self.send_response(404)
                    self.end_headers()
                    self.wfile.write(b'{"error": "no deployment in path"}')
                    return
                try:
                    with proxy._handles_lock:
                        handle = proxy._handles.get(name)
                        if handle is None:
                            handle = DeploymentHandle(name)
                            proxy._handles[name] = handle
                    if isinstance(body, dict):
                        ref = handle.remote(**body)
                    elif body is None:
                        ref = handle.remote()
                    else:
                        ref = handle.remote(body)
                    result = ray_tpu.get(ref, timeout=120)
                    payload = json.dumps({"result": result}).encode()
                    self.send_response(200)
                except Exception as e:  # noqa: BLE001
                    payload = json.dumps({"error": str(e)}).encode()
                    self.send_response(500)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                self._handle(None)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) if n else b""
                try:
                    body = json.loads(raw) if raw else None
                except json.JSONDecodeError as e:
                    payload = json.dumps(
                        {"error": f"invalid JSON body: {e}"}).encode()
                    self.send_response(400)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                self._handle(body)

        self._server = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name="serve-http").start()

    def ready(self) -> int:
        return self.port

    def stop(self) -> None:
        self._server.shutdown()
