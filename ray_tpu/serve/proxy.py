"""LEGACY threading HTTP ingress (compat shim).

The default ingress is now the per-node asyncio proxy fleet
(serve/_private/proxy_fleet/ — `serve.start_http` starts it); this
ThreadingHTTPServer actor remains only for callers that import
HTTPProxyActor directly. Its thread pool caps HTTP at ~500 req/s while
handles sustain ~1,500 (VERDICT Weak §8, BENCH_SERVE_r07/r08) and it
has no admission control: new code should go through the fleet.

reference parity: serve/_private/proxy.py:122 (per-node HTTP proxy
routing requests into deployment handles). POST/GET /<deployment-name>
with a JSON body; the body (an object → kwargs, anything else → single
positional arg) is passed to the deployment and the JSON result returned.

Request telemetry (see README "Serve request telemetry"): every request
gets a trace id — the inbound ``X-Request-Id`` header when present,
minted otherwise, always echoed back in the response header — adopted
for the handler thread so the handle submit and the replica execution
(and any nested deployment calls) share it in `ray_tpu timeline
--trace-id`. Each hop records spans (parse / route / handle wait /
serialize / write), the proxy counts
``ray_tpu_serve_requests_total{deployment,code}``, and a bounded ring
captures the slowest + all errored requests for `ray_tpu serve
requests`.

Error semantics: unknown deployment → 404, handle timeout
(`serve_request_timeout_s`, default 120s) → 504, malformed JSON → 400,
anything else → 500; every outcome still records its trace + metrics.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from time import perf_counter
from typing import Any, Dict, Optional


class HTTPProxyActor:
    def __init__(self, port: int = 8000,
                 request_timeout_s: Optional[float] = None):
        from ray_tpu._private.config import Config
        from ray_tpu.serve import _telemetry
        from ray_tpu.serve.api import DeploymentHandle

        self._handles: Dict[str, Any] = {}
        self._handles_lock = threading.Lock()
        self._timeout = float(request_timeout_s
                              if request_timeout_s is not None
                              else Config.serve_request_timeout_s)
        self._ring = _telemetry.RequestRing()
        proxy = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def _reply(self, code: int, payload: bytes,
                       trace_id: Optional[str] = None) -> None:
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                if trace_id:
                    self.send_header("X-Request-Id", trace_id)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def _handle(self, body: Any, parse_s: float = 0.0) -> None:
                import ray_tpu
                from ray_tpu._private import spans as spans_lib
                from ray_tpu.serve import _telemetry
                from ray_tpu.serve.api import DeploymentNotFound
                from ray_tpu.util import tracing
                name = self.path.strip("/").split("/")[0]
                trace_id = _telemetry.ingress_trace_id(
                    self.headers.get("X-Request-Id"))
                t_start = perf_counter()
                stages: Dict[str, float] = {"parse_s": parse_s}
                code, err = 200, None
                payload = b""
                with tracing.use_trace(trace_id):
                    with spans_lib.span("serve.proxy.request",
                                        deployment=name) as sp:
                        try:
                            if not name:
                                raise DeploymentNotFound(
                                    "no deployment in path")
                            t0 = perf_counter()
                            with proxy._handles_lock:
                                handle = proxy._handles.get(name)
                                if handle is None:
                                    handle = DeploymentHandle(name)
                                    proxy._handles[name] = handle
                            if isinstance(body, dict):
                                ref = handle.remote(**body)
                            elif body is None:
                                ref = handle.remote()
                            else:
                                ref = handle.remote(body)
                            stages["route_s"] = perf_counter() - t0
                            t0 = perf_counter()
                            result = ray_tpu.get(
                                ref, timeout=proxy._timeout)
                            stages["handle_s"] = perf_counter() - t0
                            t0 = perf_counter()
                            payload = json.dumps(
                                {"result": result}).encode()
                            stages["serialize_s"] = perf_counter() - t0
                        except DeploymentNotFound as e:
                            code, err = 404, str(e)
                            # don't let a path scan grow the handle
                            # cache (and its listener threads) one
                            # entry per bogus name forever
                            with proxy._handles_lock:
                                proxy._handles.pop(name, None)
                        except ray_tpu.exceptions.GetTimeoutError:
                            # the timeout may also be the handle's
                            # internal 30s routing fetch (controller
                            # hung) — report the time that actually
                            # elapsed, not the configured budget
                            code, err = 504, (
                                f"deployment {name!r} did not respond "
                                f"within "
                                f"{perf_counter() - t_start:.1f}s "
                                f"(request timeout "
                                f"{proxy._timeout:g}s)")
                        except Exception as e:  # noqa: BLE001
                            code, err = 500, str(e)
                        sp["code"] = code
                    if err is not None:
                        payload = json.dumps(
                            {"error": err,
                             "request_id": trace_id}).encode()
                    t0 = perf_counter()
                    try:
                        self._reply(code, payload, trace_id)
                    except Exception as e:
                        # client went away mid-write: surface it in the
                        # ring/counter as 499 (client closed request),
                        # not a phantom clean 200
                        code, err = 499, f"response write failed: {e}"
                        raise
                    finally:
                        # record AFTER the response write so the entry
                        # is complete (write_s included) when it is
                        # published — snapshot serialization must never
                        # race a mutating handler thread
                        stages["write_s"] = perf_counter() - t0
                        spans_lib.end("serve.proxy.write", t0,
                                      deployment=name,
                                      bytes=len(payload))
                        _telemetry.record_ingress(
                            proxy._ring, deployment=name or "?",
                            method="http", code=code,
                            trace_id=trace_id,
                            total_s=perf_counter() - t_start,
                            stages=stages, error=err)

            def do_GET(self):
                self._handle(None)

            def do_POST(self):
                t0 = perf_counter()
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) if n else b""
                try:
                    body = json.loads(raw) if raw else None
                except json.JSONDecodeError as e:
                    from ray_tpu.serve import _telemetry
                    trace_id = _telemetry.ingress_trace_id(
                        self.headers.get("X-Request-Id"))
                    err = f"invalid JSON body: {e}"
                    _telemetry.record_ingress(
                        proxy._ring,
                        deployment=self.path.strip("/").split("/")[0]
                        or "?",
                        method="http", code=400, trace_id=trace_id,
                        total_s=perf_counter() - t0,
                        stages={"parse_s": perf_counter() - t0},
                        error=err)
                    self._reply(400, json.dumps(
                        {"error": err,
                         "request_id": trace_id}).encode(), trace_id)
                    return
                self._handle(body, parse_s=perf_counter() - t0)

        self._server = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name="serve-http").start()

    def ready(self) -> int:
        return self.port

    def requests_snapshot(self, deployment: Optional[str] = None,
                          errors: bool = False,
                          slowest: Optional[int] = None):
        """Captured slow/errored requests (see _telemetry.RequestRing)
        — queried by util.state.serve_requests() across all proxies."""
        return self._ring.snapshot(deployment=deployment, errors=errors,
                                   slowest=slowest)

    def stop(self) -> None:
        self._server.shutdown()
