"""Serve request telemetry: RED metrics, slow/error request ring,
ingress trace ids, and harvest-time queue-depth gauges.

reference parity: serve/_private/proxy.py + metrics_utils.py — the
reference instruments every request hop with deployment-tagged
latency/queue metrics and a request-context id. Here the same ledger
rides the existing planes: span-plane records at each hop (proxy
parse/route/write, handle submit, replica queue/execute), per-deployment
RED metrics through `util.metrics` (harvested onto the cluster-merged
/metrics endpoint by _private/metrics_plane.py), and a bounded per-proxy
ring of the slowest + all errored requests behind `ray_tpu serve
requests` / /api/serve/requests / util.state.serve_requests().

Ownership of the RED metrics (one observation per request per metric —
the merged endpoint must not double-count a request that crossed
several hops):

  - ``ray_tpu_serve_requests_total{deployment,code}`` — incremented at
    the INGRESS proxy (HTTP or gRPC), where the status code is decided;
    404s and 504s that never reach a replica are still counted.
  - ``ray_tpu_serve_request_seconds{deployment}`` — observed by the
    DeploymentHandle's completion callback (submit → result ready), so
    proxy traffic and direct handle calls (deployment composition,
    bench harnesses) land in the same histogram, and a request the
    proxy abandoned at its deadline still records its true latency.
  - ``ray_tpu_serve_queue_seconds{deployment}`` — observed by the
    replica (submit wall stamp → execution start: time spent queued in
    the handle/executor path).
  - gauges ``ray_tpu_serve_handle_queue_depth`` /
    ``ray_tpu_serve_replica_queue_depth`` — exported at harvest time
    via the metrics plane's register_sampler hook; the request hot path
    never touches them.
"""

from __future__ import annotations

import heapq
import re
import threading
import uuid
import weakref
from collections import deque
from typing import Any, Dict, List, Optional

from ray_tpu.util.locks import TracedLock

# Serve-appropriate latency buckets (the registry default tops out at
# 1000s and has no sub-10ms resolution; SLO p99s live in this range).
LATENCY_BOUNDARIES = [0.005, 0.025, 0.05, 0.1, 0.25, 0.5,
                      1.0, 2.5, 5.0, 10.0]

# Inbound X-Request-Id values are adopted verbatim only when they are
# shaped like an id — anything else (oversized, control chars, spoofed
# exposition-breaking bytes) is replaced by a minted id.
_TRACE_ID_RE = re.compile(r"^[A-Za-z0-9_.\-]{1,64}$")


def mint_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def ingress_trace_id(header: Optional[str]) -> str:
    """The trace id for one ingress request: the inbound header when it
    is id-shaped, else a freshly minted one (always returned to the
    client in the response's X-Request-Id)."""
    if header and _TRACE_ID_RE.match(header):
        return header
    return mint_trace_id()


# ---------------------------------------------------------------------
# RED metrics (lazily created so merely importing serve registers
# nothing; get_or_create because proxy/handle/replica race on first use)
# ---------------------------------------------------------------------


def _counter():
    from ray_tpu.util.metrics import Counter, get_or_create
    return get_or_create(
        Counter, "ray_tpu_serve_requests_total",
        description="serve ingress requests by deployment and status "
                    "code (counted at the HTTP/gRPC proxy)",
        tag_keys=("deployment", "code"))


def _request_hist():
    from ray_tpu.util.metrics import Histogram, get_or_create
    return get_or_create(
        Histogram, "ray_tpu_serve_request_seconds",
        description="serve request latency, submit to result ready "
                    "(observed by the deployment handle)",
        boundaries=LATENCY_BOUNDARIES, tag_keys=("deployment",))


def _queue_hist():
    from ray_tpu.util.metrics import Histogram, get_or_create
    return get_or_create(
        Histogram, "ray_tpu_serve_queue_seconds",
        description="serve time-in-queue, handle submit to replica "
                    "execution start (observed by the replica)",
        boundaries=LATENCY_BOUNDARIES, tag_keys=("deployment",))


def _shed_counter():
    from ray_tpu.util.metrics import Counter, get_or_create
    return get_or_create(
        Counter, "ray_tpu_serve_shed_total",
        description="serve ingress requests shed by admission control "
                    "(503 + Retry-After / RESOURCE_EXHAUSTED), by "
                    "deployment and reason (capacity | rate_limit)",
        tag_keys=("deployment", "reason"))


def count_request(deployment: str, code: Any) -> None:
    try:
        _counter().inc(tags={"deployment": deployment,
                             "code": str(code)})
    except Exception:  # noqa: BLE001 - telemetry must never fail a request
        pass


def count_shed(deployment: str, reason: str) -> None:
    """One shed decision at an ingress proxy — first-class RED (the
    serve_shed_burn watchdog probe judges this counter's per-harvest
    delta against admitted traffic)."""
    try:
        _shed_counter().inc(tags={"deployment": deployment,
                                  "reason": reason})
    except Exception:  # noqa: BLE001 - telemetry must never fail a request
        pass


def observe_request(deployment: str, dur_s: float) -> None:
    try:
        _request_hist().observe(dur_s, tags={"deployment": deployment})
    except Exception:  # noqa: BLE001 - telemetry must never fail a request
        pass


def observe_queue(deployment: str, dur_s: float) -> None:
    try:
        _queue_hist().observe(dur_s, tags={"deployment": deployment})
    except Exception:  # noqa: BLE001 - telemetry must never fail a request
        pass


# ---------------------------------------------------------------------
# Slow/error request ring (one per proxy actor)
# ---------------------------------------------------------------------


class RequestRing:
    """Bounded capture of the requests an operator asks about first:
    every errored request (drop-oldest deque) plus the N slowest
    (min-heap on total latency). Entries are small dicts — trace id,
    deployment, method, code, per-stage breakdown, error string — and
    recording is O(log N) off the response path's critical section."""

    def __init__(self, errors_max: int = 128, slowest_max: int = 64):
        self._errors: "deque" = deque(maxlen=max(1, errors_max))
        self._slowest: List[tuple] = []  # (total_s, seq, entry) min-heap
        self._slowest_max = max(1, slowest_max)
        self._seq = 0
        self._lock = TracedLock("serve_request_ring")

    def record(self, entry: Dict[str, Any]) -> None:
        with self._lock:
            self._seq += 1
            entry["seq"] = self._seq
            if entry.get("error") is not None:
                self._errors.append(entry)
            item = (float(entry.get("total_s") or 0.0), self._seq, entry)
            if len(self._slowest) < self._slowest_max:
                heapq.heappush(self._slowest, item)
            elif item[0] > self._slowest[0][0]:
                heapq.heapreplace(self._slowest, item)

    def snapshot(self, deployment: Optional[str] = None,
                 errors: bool = False,
                 slowest: Optional[int] = None) -> List[Dict[str, Any]]:
        """Captured entries, oldest first. `errors=True` restricts to
        errored requests; `slowest=N` keeps the N slowest of the view
        (latency-descending) — the flags compose, so errors+slowest is
        the N slowest ERRORED requests; `deployment` filters any
        view."""
        with self._lock:
            errs = list(self._errors)
            slow = [e for _t, _s, e in self._slowest]
        if errors:
            out = errs
        elif slowest is not None:
            out = slow
        else:
            # merged view, deduped (an errored slow request is in both)
            seen: set = set()
            out = []
            for e in errs + slow:
                if e["seq"] in seen:
                    continue
                seen.add(e["seq"])
                out.append(e)
            out.sort(key=lambda e: e.get("ts") or 0.0)
        if deployment:
            out = [e for e in out if e.get("deployment") == deployment]
        if slowest is not None:
            out = sorted(out, key=lambda e: e.get("total_s") or 0.0,
                         reverse=True)[:slowest]
        return out


def record_ingress(ring: Optional[RequestRing], *, deployment: str,
                   method: str, code: Any, trace_id: str,
                   total_s: float, stages: Dict[str, float],
                   error: Optional[str] = None) -> Dict[str, Any]:
    """One ingress request's ledger entry: count it (RED), capture it
    (ring). `stages` must be COMPLETE when passed — the entry becomes
    visible to requests_snapshot() serialization the moment it is
    recorded, so callers must not mutate it afterwards (record after
    the response write, as both proxies do)."""
    import time
    count_request(deployment, code)
    entry = {
        "ts": time.time(),
        "trace_id": trace_id,
        "deployment": deployment,
        "method": method,
        "code": int(code),
        "error": error,
        "total_s": total_s,
        "stages": stages,
    }
    if ring is not None:
        try:
            ring.record(entry)
        except Exception:  # noqa: BLE001 - telemetry must never fail a request
            pass
    return entry


# ---------------------------------------------------------------------
# Harvest-time queue-depth gauges
# ---------------------------------------------------------------------

_handles: "weakref.WeakSet" = weakref.WeakSet()
_replicas: "weakref.WeakSet" = weakref.WeakSet()
# deployments whose gauge series this process has set: a deployment
# whose handles/replicas vanish must read 0, not freeze at its last
# nonzero depth (a phantom backlog on /metrics)
_gauged_handle_deps: set = set()
_gauged_replica_deps: set = set()
_sampler_installed = False
_sampler_lock = threading.Lock()


def _ensure_sampler() -> None:
    global _sampler_installed
    with _sampler_lock:
        if _sampler_installed:
            return
        _sampler_installed = True
    from ray_tpu._private import metrics_plane
    metrics_plane.register_sampler("serve_telemetry", _sample_gauges)


def register_handle(handle: Any) -> None:
    """Track a DeploymentHandle for the harvest-time queue-depth gauge
    (weakly: an abandoned handle drops out on its own)."""
    _handles.add(handle)
    _ensure_sampler()


def register_replica(replica: Any) -> None:
    """Track a Replica instance for the harvest-time queue-depth gauge."""
    _replicas.add(replica)
    _ensure_sampler()


def _sample_gauges() -> None:
    """Export point-in-time serve queue depths at harvest time (the
    metrics plane calls this right before snapshotting the registry —
    the request hot path never pays for it)."""
    from ray_tpu.util.metrics import Gauge, get_or_create
    handle_depth: Dict[str, float] = {}
    for h in list(_handles):
        try:
            with h._lock:
                n = sum(h._in_flight.values())
            handle_depth[h.deployment_name] = \
                handle_depth.get(h.deployment_name, 0.0) + n
        except Exception:  # noqa: BLE001 - a half-torn-down handle must
            pass           # not break the whole snapshot
    if handle_depth or _gauged_handle_deps:
        g = get_or_create(
            Gauge, "ray_tpu_serve_handle_queue_depth",
            description="in-flight serve requests tracked by this "
                        "process's deployment handles",
            tag_keys=("deployment",))
        # vanished deployments read 0, not their last nonzero depth
        for dep in _gauged_handle_deps - set(handle_depth):
            g.set(0.0, tags={"deployment": dep})
        for dep, n in handle_depth.items():
            g.set(n, tags={"deployment": dep})
        _gauged_handle_deps.update(handle_depth)
    replica_depth: Dict[str, float] = {}
    for r in list(_replicas):
        try:
            replica_depth[r.deployment_name] = \
                replica_depth.get(r.deployment_name, 0.0) \
                + float(r.ongoing_requests())
        except Exception:  # noqa: BLE001 - replica mid-shutdown
            pass
    if replica_depth or _gauged_replica_deps:
        g = get_or_create(
            Gauge, "ray_tpu_serve_replica_queue_depth",
            description="queued + executing serve requests on this "
                        "process's replica (executor default group)",
            tag_keys=("deployment",))
        for dep in _gauged_replica_deps - set(replica_depth):
            g.set(0.0, tags={"deployment": dep})
        for dep, n in replica_depth.items():
            g.set(n, tags={"deployment": dep})
        _gauged_replica_deps.update(replica_depth)
