"""Serve controller: reconciles deployment state to replica actors.

reference parity: serve/_private/controller.py:87 (ServeController actor)
+ deployment_state.py:1149 (DeploymentState reconciliation: target
replicas vs running replicas, health checks, replacements) +
autoscaling_policy.py (queue-depth driven scaling between min/max).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional
from ray_tpu.util.locks import TracedLock

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"


def replica_ping(replica) -> bool:
    import ray_tpu
    try:
        return ray_tpu.get(replica.ping.remote(), timeout=10) == "pong"
    except Exception:  # noqa: BLE001
        return False


def _control_group(fn):
    """Tag a Replica method onto the 'control' concurrency group (the
    plain ray_tpu.method decorator, applied without importing ray_tpu at
    module import time)."""
    fn.__ray_tpu_method_options__ = {"concurrency_group": "control"}
    return fn


# current request's multiplexed model id (reference
# serve/_private/replica.py request context + serve.api
# get_multiplexed_model_id)
_current_model_id = threading.local()


def get_multiplexed_model_id() -> str:
    return getattr(_current_model_id, "value", "")


class _MultiplexWrapper:
    """Per-replica LRU of loaded models behind a user loader fn
    (reference serve/api.py @serve.multiplexed + multiplex.py
    _ModelMultiplexWrapper)."""

    def __init__(self, loader, max_num_models_per_replica: int = 3):
        self.loader = loader
        self.max_models = max(1, max_num_models_per_replica)
        self.models: Dict[str, Any] = {}   # insertion order = LRU
        self._loading: Dict[str, threading.Event] = {}
        self._lock = TracedLock("serve_model_cache")

    def load(self, owner, model_id: str):
        # per-model-id load serialization: concurrent requests for the
        # same missing model must not both run the (possibly HBM-
        # hungry) loader — the reference wrapper serializes loads too.
        # Waiters loop: on wake they re-check the cache (the loader
        # publishes the model BEFORE setting the gate), and if the
        # loader failed exactly one waiter becomes the next loader.
        while True:
            with self._lock:
                if model_id in self.models:
                    model = self.models.pop(model_id)
                    self.models[model_id] = model  # refresh LRU position
                    return model
                gate = self._loading.get(model_id)
                if gate is None:
                    gate = threading.Event()
                    self._loading[model_id] = gate
                    break  # we are the loader
            gate.wait(timeout=600)
        try:
            model = self.loader(owner, model_id)
            with self._lock:
                self.models[model_id] = model
                while len(self.models) > self.max_models:
                    evicted_id = next(iter(self.models))
                    self.models.pop(evicted_id)
                    logger.info(
                        "multiplex: evicted model %s (dropped; "
                        "resources release with its refcount)",
                        evicted_id)
            return model
        finally:
            # publish-then-release ordering: models[...] is already set
            # (on success) when waiters wake
            with self._lock:
                self._loading.pop(model_id, None)
            gate.set()

    def loaded_ids(self) -> List[str]:
        with self._lock:
            return list(self.models)


def multiplexed(_fn=None, *, max_num_models_per_replica: int = 3):
    """Decorator for a deployment method that loads a model by id; the
    wrapper caches up to max_num_models_per_replica loaded models per
    replica with LRU eviction (reference serve.multiplexed)."""

    def wrap(fn):
        state_attr = f"__mux_{fn.__name__}"

        def getter(self, model_id: str):
            mux = getattr(self, state_attr, None)
            if mux is None:
                mux = _MultiplexWrapper(fn, max_num_models_per_replica)
                setattr(self, state_attr, mux)
            return mux.load(self, model_id)

        getter.__mux_marker__ = True
        getter.__wrapped__ = fn
        return getter

    if _fn is not None:
        return wrap(_fn)
    return wrap


class Replica:
    """The per-replica actor: hosts one instance of the user deployment
    (reference serve/_private/replica.py). Request telemetry (README
    "Serve request telemetry"): each request records its time-in-queue
    (the handle's submit wall stamp → execution start, into
    ``ray_tpu_serve_queue_seconds{deployment}`` + a span) and its
    execution as a ``serve.replica.execute`` span — both carry the
    ingress trace id, which the executor already restored from the task
    spec before this method runs."""

    def __init__(self, target_blob: bytes, init_args: tuple,
                 init_kwargs: Dict[str, Any],
                 deployment_name: str = ""):
        import cloudpickle
        target = cloudpickle.loads(target_blob)
        if isinstance(target, type):
            self._callable = target(*init_args, **init_kwargs)
        else:
            self._callable = target
        self.deployment_name = deployment_name
        self._in_flight = 0
        self._total = 0
        self._lock = TracedLock("serve_replica")
        from ray_tpu.serve import _telemetry
        _telemetry.register_replica(self)

    @_control_group
    def ping(self) -> str:
        return "pong"

    def ongoing_requests(self) -> int:
        """Queued + executing on this worker's default executor group —
        the harvest-time replica queue-depth gauge reads this (NOT an
        actor call: runs in-process from the metrics sampler)."""
        from ray_tpu._private import worker as worker_mod
        w = worker_mod.global_worker_or_none()
        ex = w.core_worker.executor if w is not None else None
        return ex.queue_depth("") if ex is not None else 0

    def _record_queue_time(self, submit_ts) -> None:
        if not submit_ts:
            return
        import time as _time

        from ray_tpu._private import spans as spans_lib
        from ray_tpu.serve import _telemetry
        # cross-process interval: the handle stamped WALL time (monotonic
        # clocks are per-process); same-host skew is negligible next to
        # queueing delay  # graftlint: disable=RT010
        queue_s = max(0.0, _time.time() - submit_ts)
        _telemetry.observe_queue(self.deployment_name, queue_s)
        spans_lib.complete("serve.replica.queue", queue_s,
                           deployment=self.deployment_name)

    def handle_request(self, args: tuple, kwargs: Dict[str, Any],
                       model_id: str = "", submit_ts=None) -> Any:
        from ray_tpu._private import spans as spans_lib
        self._record_queue_time(submit_ts)
        with self._lock:
            self._in_flight += 1
            self._total += 1
        _current_model_id.value = model_id
        try:
            with spans_lib.span("serve.replica.execute",
                                deployment=self.deployment_name):
                fn = self._callable
                if not callable(fn):
                    raise TypeError(
                        f"deployment target {fn!r} is not callable")
                return fn(*args, **kwargs)
        finally:
            _current_model_id.value = ""
            with self._lock:
                self._in_flight -= 1

    def handle_request_batch(self, requests: List[Any],
                             model_id: str = "",
                             submit_ts=None) -> List[tuple]:
        """Proxy-coalesced execution: `requests` is a list of single
        positional args fused by an ingress proxy (proxy_fleet
        _Coalescer) into ONE task submit. A @serve.batch-decorated
        __call__ gets every item enqueued BEFORE any result is awaited,
        so the whole proxy batch lands in one fused forward pass;
        plain callables run the items in order (still one task's
        overhead instead of N). Returns [(ok, result-or-error), ...] —
        per-item errors must not fail the co-batched strangers."""
        from ray_tpu._private import spans as spans_lib
        from ray_tpu._private.config import Config
        self._record_queue_time(submit_ts)
        with self._lock:
            self._in_flight += 1
            self._total += len(requests)
        _current_model_id.value = model_id
        out: List[tuple] = []
        try:
            with spans_lib.span("serve.replica.execute",
                                deployment=self.deployment_name,
                                batch=len(requests)):
                fn = self._callable
                if not callable(fn):
                    raise TypeError(
                        f"deployment target {fn!r} is not callable")
                # class deployments only: the @serve.batch wrapper is
                # the class's __call__ (function deployments can't
                # batch — the wrapper needs an owner for its queue)
                meth = getattr(type(fn), "__call__", None)
                submit_many = getattr(meth, "_serve_batch_submit_many",
                                      None)
                if submit_many is not None:
                    futs = submit_many(fn, list(requests))
                    # ONE shared deadline for the whole batch: a
                    # wedged handler costs one timeout, not N of them
                    # serially (which would pin this executor slot —
                    # and block Replica.drain — for N x timeout)
                    deadline = time.monotonic() \
                        + Config.serve_request_timeout_s
                    for f in futs:
                        try:
                            out.append((True, f.result(
                                timeout=max(0.0, deadline
                                            - time.monotonic()))))
                        except Exception as e:  # noqa: BLE001
                            out.append((False,
                                        f"{type(e).__name__}: {e}"))
                else:
                    for item in requests:
                        try:
                            out.append((True, fn(item)))
                        except Exception as e:  # noqa: BLE001
                            out.append((False,
                                        f"{type(e).__name__}: {e}"))
            return out
        finally:
            _current_model_id.value = ""
            with self._lock:
                self._in_flight -= 1

    @_control_group
    def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful-shutdown gate (rolling updates): poll until every
        queued + executing request on the default group has finished.
        Runs on the control group so it can observe the default group
        draining; new work stops arriving because the controller bumped
        the routing snapshot away from this replica first."""
        import time as _time
        deadline = _time.monotonic() + timeout_s
        while _time.monotonic() < deadline:
            if self.ongoing_requests() == 0:
                with self._lock:
                    if self._in_flight == 0:
                        return True
            _time.sleep(0.05)
        return False

    def handle_request_stream(self, args: tuple,
                              kwargs: Dict[str, Any],
                              model_id: str = "", submit_ts=None):
        """Generator variant (reference serve streaming responses /
        proxy.py:556): the deployment callable returns an iterable and
        chunks stream back as they are produced (num_returns=
        "streaming" on the caller side)."""
        from ray_tpu._private import spans as spans_lib
        self._record_queue_time(submit_ts)
        with self._lock:
            self._in_flight += 1
            self._total += 1
        _current_model_id.value = model_id
        try:
            with spans_lib.span("serve.replica.execute",
                                deployment=self.deployment_name,
                                stream=True):
                fn = self._callable
                out = fn(*args, **kwargs)
                for chunk in out:
                    yield chunk
        finally:
            _current_model_id.value = ""
            with self._lock:
                self._in_flight -= 1

    @_control_group
    def multiplexed_model_ids(self) -> List[str]:
        """Model ids loaded by any @multiplexed loader on the target
        (router affinity signal; reference multiplex router prefers
        replicas that already hold the model)."""
        out: List[str] = []
        target = self._callable
        for v in vars(target).values():
            if isinstance(v, _MultiplexWrapper):
                out.extend(v.loaded_ids())
        return out

    @_control_group
    def queue_len(self) -> int:
        """Server-side ongoing count: requests executing + waiting in
        this replica's default-group queue. Runs on the dedicated
        "control" concurrency group so it answers instantly even when
        every handle_request slot is saturated (reference: replica
        queue-length probe consumed by router.py:893
        PowerOfTwoChoicesReplicaScheduler)."""
        import ray_tpu
        return ray_tpu.get_runtime_context().get_task_queue_depth("")

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {"in_flight": self._in_flight, "total": self._total}


@dataclass
class _DeploymentState:
    name: str
    target_blob: bytes
    init_args: tuple
    init_kwargs: Dict[str, Any]
    target_replicas: int
    max_concurrent_queries: int
    ray_actor_options: Dict[str, Any]
    autoscaling: Optional[Any] = None
    # ingress admission control (proxy_fleet/admission.py): queued
    # requests admitted beyond replica capacity (-1 = config default)
    # and a per-proxy token-bucket rate limit (0 = unlimited)
    max_queued_requests: int = -1
    rate_limit_rps: float = 0.0
    # proxy-side request coalescing: True when the deployment's
    # __call__ is @serve.batch-decorated (detected at serve.run time)
    coalesce: bool = False
    replicas: List[Any] = field(default_factory=list)
    deleted: bool = False
    # sustained-condition tracking for autoscaling delays
    high_since: Optional[float] = None
    low_since: Optional[float] = None
    # serializes reconciliation per deployment: deploy()/delete() on RPC
    # threads race the background reconcile loop otherwise, double-
    # starting replicas and orphaning the losers
    op_lock: threading.Lock = field(default_factory=threading.Lock)


class ServeController:
    """Named actor owning all deployment state; a reconcile thread keeps
    running replicas == target and applies autoscaling decisions."""

    RECONCILE_PERIOD_S = 1.0

    def __init__(self) -> None:
        from ray_tpu.serve._private.proxy_fleet.fleet import (
            ProxyFleetManager)
        self._deployments: Dict[str, _DeploymentState] = {}
        self._lock = TracedLock("serve_controller")
        self._stop = threading.Event()
        # long-poll state (reference serve/_private/long_poll.py:30
        # LongPollHost): per-deployment snapshot ids; listeners block on
        # the condition until a watched id advances.
        self._lp_cond = threading.Condition()
        self._snapshots: Dict[str, int] = {}
        # ingress fleet (proxy_fleet/fleet.py): reconciled on its OWN
        # thread once start_proxy_fleet arms it — a proxy drain (up to
        # serve_drain_timeout_s) must never stall replica repair or
        # autoscaling on the deployment loop
        self._fleet = ProxyFleetManager()
        threading.Thread(target=self._reconcile_loop, daemon=True,
                         name="serve-reconcile").start()
        threading.Thread(target=self._fleet_loop, daemon=True,
                         name="serve-fleet-reconcile").start()

    # ---- ingress fleet ----------------------------------------------

    def _alive_node_ids(self) -> List[str]:
        from ray_tpu._private import worker as worker_mod
        gcs = worker_mod.global_worker().core_worker._gcs
        return [n.node_id.hex() for n in gcs.call("get_all_nodes")
                if n.alive]

    def start_proxy_fleet(self, http_port: Optional[int] = None,
                          grpc_port: Optional[int] = None,
                          request_timeout_s: Optional[float] = None
                          ) -> Dict[str, Any]:
        """Arm (or reconfigure) the ingress fleet and reconcile it NOW
        so the caller gets live endpoints back. Parameters are
        keep-if-None; a changed config rolls proxies node-by-node on
        subsequent reconcile rounds."""
        self._fleet.ensure(http_port=http_port, grpc_port=grpc_port,
                           request_timeout_s=request_timeout_s)
        self._fleet.reconcile(self._alive_node_ids())
        return self._fleet.status()

    def fleet_status(self) -> Dict[str, Any]:
        return self._fleet.status()

    def drain_proxy(self, node_id: str) -> bool:
        """Drain + deregister one node's proxy (node-removal path)."""
        return self._fleet.drain_node(node_id)

    def stop_proxy_fleet(self) -> None:
        self._fleet.stop_all()

    # ---- long-poll push ---------------------------------------------

    def _bump_snapshot(self, name: str) -> None:
        with self._lp_cond:
            self._snapshots[name] = self._snapshots.get(name, 0) + 1
            self._lp_cond.notify_all()

    @_control_group
    def listen_for_change(self, keys: Dict[str, int],
                          timeout_s: float = 30.0) -> Dict[str, Any]:
        """Block until any watched deployment's snapshot id advances past
        the caller's, then return {name: (new_id, routing_info)}; {} on
        timeout (the caller re-arms). This is the push channel handles
        use instead of polling get_routing_info (reference
        long_poll.py:30 LongPollHost.listen_for_change). Runs on the
        'control' concurrency group so armed listeners never starve
        deploy/delete calls."""
        deadline = time.monotonic() + min(timeout_s, 60.0)
        while True:
            with self._lp_cond:
                changed = {k: self._snapshots.get(k, 0) for k in keys
                           if self._snapshots.get(k, 0) > keys[k]}
                if not changed:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return {}
                    self._lp_cond.wait(remaining)
                    continue
            # build result outside the condition (get_routing_info takes
            # the state lock; never nest it under _lp_cond)
            return {k: (v, self.get_routing_info(k))
                    for k, v in changed.items()}

    # ---- API --------------------------------------------------------

    def deploy(self, name: str, target_blob: bytes, init_args: tuple,
               init_kwargs: Dict[str, Any], num_replicas: int,
               max_concurrent_queries: int,
               ray_actor_options: Dict[str, Any],
               autoscaling: Optional[Any] = None,
               max_queued_requests: int = -1,
               rate_limit_rps: float = 0.0,
               coalesce: bool = False) -> None:
        with self._lock:
            old = self._deployments.get(name)
            state = _DeploymentState(
                name=name, target_blob=target_blob, init_args=init_args,
                init_kwargs=init_kwargs, target_replicas=num_replicas,
                max_concurrent_queries=max_concurrent_queries,
                ray_actor_options=dict(ray_actor_options),
                autoscaling=autoscaling,
                max_queued_requests=max_queued_requests,
                rate_limit_rps=rate_limit_rps, coalesce=coalesce)
            self._deployments[name] = state
        # Rolling update, new-first (reference deployment_state rolling
        # replace): start the NEW replica set, publish it (snapshot
        # bump pushes every handle onto the new set), and only then
        # drain + stop the old one — in-flight requests on old replicas
        # finish instead of dying with the actor, so a redeploy under
        # load surfaces zero 5xx.
        if old is not None:
            old.deleted = True
        self._reconcile_one(state)
        self._bump_snapshot(name)
        if old is not None:
            with old.op_lock:
                self._drain_replicas(old.replicas)
                self._stop_replicas(old.replicas)
                old.replicas = []

    def get_replicas(self, name: str) -> List[Any]:
        with self._lock:
            state = self._deployments.get(name)
            return list(state.replicas) if state else []

    def get_routing_info(self, name: str) -> Dict[str, Any]:
        """Replica set + limits the router needs (reference: the long
        poll updates handles receive from the controller). Carries the
        deployment's snapshot_id so handles can discard stale responses
        (a slow poll must not overwrite a newer pushed set)."""
        with self._lp_cond:
            snap = self._snapshots.get(name, 0)
        with self._lock:
            state = self._deployments.get(name)
            if state is None:
                # exists=False routes the handle's empty-replica failure
                # to DeploymentNotFound (ingress 404), distinct from a
                # known deployment transiently at zero replicas
                return {"replicas": [], "max_concurrent_queries": 0,
                        "snapshot_id": snap, "exists": False}
            return {"replicas": list(state.replicas),
                    "max_concurrent_queries": state.max_concurrent_queries,
                    "snapshot_id": snap, "exists": True,
                    # ingress admission + coalescing hints (the proxy
                    # fleet derives per-deployment limits from these)
                    "max_queued_requests": state.max_queued_requests,
                    "rate_limit_rps": state.rate_limit_rps,
                    "coalesce": state.coalesce}

    def list_deployments(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {n: {"target_replicas": s.target_replicas,
                        "running_replicas": len(s.replicas)}
                    for n, s in self._deployments.items()}

    def delete_deployment(self, name: str) -> None:
        with self._lock:
            state = self._deployments.pop(name, None)
        if state is not None:
            state.deleted = True
            with state.op_lock:  # wait out any in-flight reconcile
                self._stop_replicas(state.replicas)
                state.replicas = []
            self._bump_snapshot(name)

    def shutdown(self) -> None:
        self._stop.set()
        try:
            self._fleet.stop_all()
        except Exception:  # noqa: BLE001 - proxies die with the cluster
            logger.exception("proxy fleet stop failed during shutdown")
        with self._lock:
            states = list(self._deployments.values())
            self._deployments.clear()
        for s in states:
            self._stop_replicas(s.replicas)

    # ---- reconciliation --------------------------------------------

    def _start_replica(self, state: _DeploymentState):
        import ray_tpu
        cls = ray_tpu.remote(Replica)
        opts: Dict[str, Any] = {"num_cpus": 0.1}
        opts.update(state.ray_actor_options)
        opts["max_concurrency"] = state.max_concurrent_queries
        # control group: health pings + queue-length probes stay
        # responsive while all request slots are saturated (merged so
        # user-declared groups in ray_actor_options survive)
        opts["concurrency_groups"] = {
            **(opts.get("concurrency_groups") or {}), "control": 2}
        return cls.options(**opts).remote(
            state.target_blob, state.init_args, state.init_kwargs,
            deployment_name=state.name)

    def _stop_replicas(self, replicas: List[Any]) -> None:
        import ray_tpu
        for r in replicas:
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001 - replica already dead
                pass

    def _drain_replicas(self, replicas: List[Any]) -> None:
        """Wait (bounded) for every replica's queued + executing
        requests to finish before it is stopped — the rolling-update
        half of the zero-5xx contract. One batched wait bounds the
        whole drain instead of timeout x replicas."""
        import ray_tpu
        from ray_tpu._private.config import Config
        budget = Config.serve_drain_timeout_s
        drains = []
        for r in replicas:
            try:
                drains.append(r.drain.remote(budget))
            except Exception:  # noqa: BLE001 — dead replica has
                pass           # nothing in flight to wait for
        if drains:
            ray_tpu.wait(drains, num_returns=len(drains),
                         timeout=budget + 15)

    def _reconcile_one(self, state: _DeploymentState) -> None:
        import ray_tpu
        with state.op_lock:
            if state.deleted:
                return
            # replace dead replicas (reference deployment_state checks)
            with self._lock:
                replicas = list(state.replicas)
            alive = []
            for r in replicas:
                if replica_ping(r):
                    alive.append(r)
            while len(alive) < state.target_replicas:
                alive.append(self._start_replica(state))
            extra = alive[state.target_replicas:]
            alive = alive[:state.target_replicas]
            self._stop_replicas(extra)
            # wait for newly started replicas to answer — one batched
            # wait bounds the whole rollout by 120s instead of 120s per
            # replica (found by graftlint RT002); submits stay guarded
            # per replica so one stuck/full replica can't fail deploy()
            pings = []
            for r in alive:
                try:
                    pings.append(r.ping.remote())
                except Exception:  # noqa: BLE001 — e.g. pending-calls full
                    pass
            if pings:
                ray_tpu.wait(pings, num_returns=len(pings), timeout=120)
            with self._lock:
                if state.deleted:
                    # deleted while we were reconciling: the DELETER
                    # (redeploy/delete_deployment) owns these replicas
                    # — it drains then stops them under op_lock after
                    # us. Stopping here would skip the drain and kill
                    # in-flight requests mid-rolling-update.
                    state.replicas = alive
                    changed = False
                else:
                    changed = [id(r) for r in state.replicas] != \
                        [id(r) for r in alive]
                    state.replicas = alive
        if changed:  # replica set moved: push to long-poll listeners
            self._bump_snapshot(state.name)

    def _autoscale_one(self, state: _DeploymentState) -> None:
        import ray_tpu
        cfg = state.autoscaling
        if cfg is None or not state.replicas:
            return
        try:
            stats = ray_tpu.get(
                [r.stats.remote() for r in state.replicas], timeout=30)
        except Exception:  # noqa: BLE001
            return
        avg_in_flight = sum(s["in_flight"] for s in stats) / len(stats)
        now = time.monotonic()
        # Sustained-condition delays (reference autoscaling_policy): the
        # breach must HOLD for the delay window, not merely postdate the
        # previous scaling event — one bursty sample must not scale.
        high = avg_in_flight > cfg.target_ongoing_requests
        low = avg_in_flight < cfg.target_ongoing_requests / 2
        state.high_since = (state.high_since or now) if high else None
        state.low_since = (state.low_since or now) if low else None
        if high and state.target_replicas < cfg.max_replicas and \
                now - state.high_since >= cfg.upscale_delay_s:
            state.target_replicas += 1
            state.high_since = now
            logger.info("serve: scaling %s up to %d (avg in-flight %.1f)",
                        state.name, state.target_replicas, avg_in_flight)
        elif low and state.target_replicas > cfg.min_replicas and \
                now - state.low_since >= cfg.downscale_delay_s:
            state.target_replicas -= 1
            state.low_since = now
            logger.info("serve: scaling %s down to %d",
                        state.name, state.target_replicas)

    def _reconcile_loop(self) -> None:
        while not self._stop.wait(self.RECONCILE_PERIOD_S):
            with self._lock:
                states = list(self._deployments.values())
            for state in states:
                try:
                    self._autoscale_one(state)
                    self._reconcile_one(state)
                except Exception:  # noqa: BLE001
                    logger.exception("serve reconcile failed for %s",
                                     state.name)

    def _fleet_loop(self) -> None:
        while not self._stop.wait(self.RECONCILE_PERIOD_S):
            if not self._fleet.enabled:
                continue  # don't pay a GCS node-list RPC per second
            try:          # for a fleet nobody armed
                self._fleet.reconcile(self._alive_node_ids())
            except Exception:  # noqa: BLE001
                logger.exception("serve fleet reconcile failed")
