"""Serve public API: @deployment, run, handles, HTTP ingress.

reference parity: python/ray/serve/api.py (serve.deployment / serve.run)
+ handle API (serve/handle.py). The controller is a named actor; handles
resolve replica sets through it and route power-of-two-choices
(reference router.py:893 PowerOfTwoChoicesReplicaScheduler).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.serve.controller import (CONTROLLER_NAME, ServeController,
                                      replica_ping)

_NAMESPACE = "serve"


class DeploymentNotFound(Exception):
    """No deployment by that name is registered with the controller —
    the ingress proxies map this to 404 / NOT_FOUND (a missing route is
    the CLIENT's error; only real replica/infrastructure failures may
    surface as 5xx)."""


def _get_or_create_controller():
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME, namespace=_NAMESPACE)
    except Exception:  # noqa: BLE001 - not running yet
        pass
    cls = ray_tpu.remote(ServeController)
    try:
        # "control" group hosts blocked listen_for_change long-polls;
        # deploy/delete/get_routing_info stay responsive on the default
        # group however many listeners are armed.
        return cls.options(name=CONTROLLER_NAME, namespace=_NAMESPACE,
                           num_cpus=0.1, max_concurrency=8,
                           concurrency_groups={"control": 24}).remote()
    except ValueError:
        # raced another creator; the name is now taken
        return ray_tpu.get_actor(CONTROLLER_NAME, namespace=_NAMESPACE)


@dataclass
class AutoscalingConfig:
    """reference serve/config.py AutoscalingConfig (queue-depth driven)."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 2.0
    downscale_delay_s: float = 10.0


@dataclass
class Deployment:
    """The declarative unit (reference serve/deployment.py Deployment)."""

    func_or_class: Any
    name: str
    num_replicas: int = 1
    max_concurrent_queries: int = 16
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    autoscaling_config: Optional[AutoscalingConfig] = None
    # Ingress admission control (proxy_fleet/admission.py): requests
    # admitted beyond replica capacity before the proxies shed with
    # 503 + Retry-After (-1 = Config.serve_max_queued_per_deployment),
    # and a per-proxy token-bucket rate limit in req/s (0 = unlimited).
    max_queued_requests: int = -1
    rate_limit_rps: float = 0.0

    def options(self, **kwargs: Any) -> "Deployment":
        import copy
        new = copy.copy(self)
        for k, v in kwargs.items():
            if not hasattr(new, k):
                raise ValueError(f"unknown deployment option {k!r}")
            setattr(new, k, v)
        return new

    def bind(self, *args: Any, **kwargs: Any) -> "Application":
        return Application(self, args, kwargs)


@dataclass
class Application:
    deployment: Deployment
    init_args: tuple = ()
    init_kwargs: Dict[str, Any] = field(default_factory=dict)


def deployment(_func_or_class: Any = None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_concurrent_queries: int = 16,
               ray_actor_options: Optional[Dict[str, Any]] = None,
               autoscaling_config: Optional[AutoscalingConfig] = None,
               max_queued_requests: int = -1,
               rate_limit_rps: float = 0.0):
    """@serve.deployment decorator (reference api.py:deployment)."""

    def wrap(target: Any) -> Deployment:
        return Deployment(
            func_or_class=target,
            name=name or getattr(target, "__name__", "deployment"),
            num_replicas=num_replicas,
            max_concurrent_queries=max_concurrent_queries,
            ray_actor_options=dict(ray_actor_options or {}),
            autoscaling_config=autoscaling_config,
            max_queued_requests=max_queued_requests,
            rate_limit_rps=rate_limit_rps)

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


class DeploymentHandle:
    """Client-side handle with queue-aware power-of-two-choices routing
    (reference router.py:893 PowerOfTwoChoicesReplicaScheduler): pick
    two random replicas, probe each one's SERVER-SIDE queue length
    (executing + queued, reported by the replica's control concurrency
    group — visible work from every caller, not just this handle), and
    send to the shorter queue. Probes are cached briefly and adjusted
    by this handle's own in-flight deltas between probes; replicas at
    or over max_concurrent_queries are avoided while any candidate has
    room."""

    REFRESH_PERIOD_S = 2.0
    PROBE_TTL_S = 0.25
    PROBE_TIMEOUT_S = 2.0

    def __init__(self, deployment_name: str, controller=None):
        self.deployment_name = deployment_name
        self._controller = controller or _get_or_create_controller()
        self._replicas: List[Any] = []
        self._max_queries = 0  # 0 = unknown/unlimited
        # replica actor id -> (stamp, loaded multiplexed model ids)
        self._model_cache: Dict[str, Any] = {}
        # in-flight keyed by replica ACTOR id (stable across replica-set
        # refreshes; index-keyed counts would drift onto the wrong actor
        # whenever the controller replaces a dead replica)
        self._in_flight: Dict[str, int] = {}
        # last probed server-side queue length + local delta since probe
        self._probed: Dict[str, float] = {}   # key -> (stamp)
        self._probe_len: Dict[str, int] = {}  # key -> server queue len
        self._probe_delta: Dict[str, int] = {}  # sends since probe
        self._lock = threading.Lock()
        self._refresh_lock = threading.Lock()
        # Lazy first refresh (on first .remote()): an eager call home
        # would deadlock when a handle is reconstructed INSIDE the
        # controller's own handler thread (deployment composition passes
        # handles through deploy()'s init args).
        self._last_refresh = 0.0
        self._listener_started = False
        # False once the controller reports the name unknown/deleted —
        # routes _pick's empty-replica failure to DeploymentNotFound
        # (ingress 404) instead of a generic 500
        self._exists = True
        # request telemetry: harvest-time queue-depth gauge (weak
        # registration; see serve/_telemetry.py)
        from ray_tpu.serve import _telemetry
        _telemetry.register_handle(self)

    def _refresh(self, force: bool = False) -> None:
        now = time.monotonic()
        # the freshness short-circuit only applies once we HAVE replicas:
        # a concurrent first caller must block for the in-flight fetch
        # rather than race ahead into an empty replica list
        if not force and self._replicas and \
                now - self._last_refresh < self.REFRESH_PERIOD_S:
            return
        with self._refresh_lock:
            if self._replicas and \
                    time.monotonic() - self._last_refresh < self.REFRESH_PERIOD_S:
                return  # another thread refreshed while we waited
            # singleflight by design: _refresh_lock exists ONLY to make
            # concurrent first callers block for this one in-flight
            # controller fetch instead of racing into an empty replica
            # list; no other state hides behind it
            info = ray_tpu.get(  # graftlint: disable=RT015
                self._controller.get_routing_info.remote(
                    self.deployment_name), timeout=30)
            self._apply_routing_info(info)
            self._last_refresh = time.monotonic()
            # no listener for a name the controller doesn't know: a
            # 404 flood must not spawn a parked thread per request
            # (the next successful refresh arms it)
            if self._exists:
                self._ensure_listener()

    def _apply_routing_info(self, info: Dict[str, Any]) -> None:
        replicas = info["replicas"]
        with self._lock:
            # snapshot ordering guard: a slow poll response racing the
            # push listener must not roll the replica set back
            version = info.get("snapshot_id", 0)
            if version < getattr(self, "_routing_version", 0):
                return
            self._routing_version = version
            self._replicas = replicas
            self._exists = bool(info.get("exists", True))
            self._max_queries = info.get("max_concurrent_queries", 0)
            # admission/coalescing hints for the ingress fleet (the
            # proxy derives per-deployment shed limits from these)
            self._routing_extra = {
                "replica_count": len(replicas),
                "max_concurrent_queries":
                    info.get("max_concurrent_queries", 0) or 16,
                "max_queued_requests":
                    info.get("max_queued_requests", -1),
                "rate_limit_rps": info.get("rate_limit_rps", 0.0),
                "coalesce": bool(info.get("coalesce", False)),
            }
            live = {r._actor_id.hex() for r in replicas}
            self._in_flight = {k: v for k, v in self._in_flight.items()
                               if k in live}
            self._model_cache = {
                k: v for k, v in self._model_cache.items()
                if k in live}

    # ---- long-poll push (reference long_poll.py:30 LongPollClient) --
    def _ensure_listener(self) -> None:
        """Start the push listener: a daemon thread parked in the
        controller's listen_for_change, applying routing updates the
        moment they happen instead of at the next REFRESH_PERIOD poll.
        Holds only a weakref so an abandoned handle's thread exits."""
        if self._listener_started:
            return
        self._listener_started = True
        import weakref
        ref = weakref.ref(self)
        threading.Thread(target=_listen_loop, args=(ref,), daemon=True,
                         name=f"serve-listen-{self.deployment_name}"
                         ).start()

    def __reduce__(self):
        # picklable so deployments can compose: a replica holding a
        # handle to a downstream deployment (reference serve app graphs)
        # reconstructs it against its own controller connection
        return (DeploymentHandle, (self.deployment_name,))

    def _queue_len(self, replica) -> int:
        """Server-side ongoing count for one replica, probe-cached for
        PROBE_TTL_S with local sends since the probe added on top."""
        key = replica._actor_id.hex()
        now = time.monotonic()
        with self._lock:
            fresh = now - self._probed.get(key, 0.0) < self.PROBE_TTL_S
            if fresh:
                return (self._probe_len.get(key, 0)
                        + self._probe_delta.get(key, 0))
        try:
            qlen = ray_tpu.get(replica.queue_len.remote(),
                               timeout=self.PROBE_TIMEOUT_S)
        except Exception:  # noqa: BLE001 — probe failure: fall back to
            # the handle-local count, and NEGATIVE-CACHE the failure so
            # a dead/restarting replica costs one timeout per TTL, not
            # one per request
            with self._lock:
                self._probed[key] = time.monotonic()
                self._probe_len[key] = self._in_flight.get(key, 0)
                self._probe_delta[key] = 0
                return self._probe_len[key]
        with self._lock:
            self._probed[key] = time.monotonic()
            self._probe_len[key] = int(qlen)
            self._probe_delta[key] = 0
            return int(qlen)

    def _model_ids(self, replica) -> List[str]:
        """Loaded multiplexed-model ids for one replica, probe-cached."""
        key = replica._actor_id.hex()
        now = time.monotonic()
        with self._lock:
            cached = self._model_cache.get(key)
            if cached is not None and now - cached[0] < 2.0:
                return cached[1]
        try:
            ids = list(ray_tpu.get(
                replica.multiplexed_model_ids.remote(),
                timeout=self.PROBE_TIMEOUT_S))
        except Exception:  # noqa: BLE001
            ids = []
        with self._lock:
            self._model_cache[key] = (time.monotonic(), ids)
        return ids

    def _pick(self, model_id: str = ""):
        with self._lock:
            n = len(self._replicas)
            if n == 0:
                if not self._exists:
                    raise DeploymentNotFound(
                        f"no deployment named "
                        f"{self.deployment_name!r}")
                raise RuntimeError(
                    f"deployment {self.deployment_name!r} has no replicas")
            if n == 1:
                return self._replicas[0]
            a, b = random.sample(self._replicas, 2)
            limit = self._max_queries
        if model_id:
            # model multiplexing affinity (reference multiplex router):
            # prefer the candidate that already holds the model
            a_has = model_id in self._model_ids(a)
            b_has = model_id in self._model_ids(b)
            if a_has != b_has:
                return a if a_has else b
        la, lb = self._queue_len(a), self._queue_len(b)
        # avoid saturated replicas while the other candidate has room
        # (server-side max_concurrent_queries enforcement at the router,
        # reference router.py:893 candidate filtering)
        if limit > 0:
            if la >= limit and lb < limit:
                return b
            if lb >= limit and la < limit:
                return a
        return a if la <= lb else b

    def options(self, *, multiplexed_model_id: str = "",
                stream: bool = False) -> "_HandleOptions":
        """Per-call routing options (reference handle .options():
        multiplexed_model_id steers to replicas holding the model;
        stream=True returns a generator of response chunks)."""
        return _HandleOptions(self, multiplexed_model_id, stream)

    def remote(self, *args: Any, **kwargs: Any):
        return self._submit(args, kwargs, model_id="", stream=False)

    # ---- shared in-flight/probe accounting (router load estimates:
    # _submit and _submit_batch must never diverge here) -------------
    def _track_inflight(self, key: str) -> None:
        with self._lock:
            self._in_flight[key] = self._in_flight.get(key, 0) + 1
            self._probe_delta[key] = self._probe_delta.get(key, 0) + 1

    def _untrack_inflight(self, key: str) -> None:
        with self._lock:
            self._in_flight[key] = max(
                0, self._in_flight.get(key, 1) - 1)
            self._probe_delta[key] = self._probe_delta.get(key, 1) - 1

    def _submit(self, args: tuple, kwargs: Dict[str, Any], *,
                model_id: str, stream: bool):
        from ray_tpu._private import spans as _spans_lib
        from ray_tpu.serve import _telemetry
        t_submit = time.monotonic()
        with _spans_lib.span("serve.handle.submit",
                             deployment=self.deployment_name):
            self._refresh()
            replica = self._pick(model_id)
            key = replica._actor_id.hex()
            self._track_inflight(key)
            if stream:
                method = replica.handle_request_stream.options(
                    num_returns="streaming")
            else:
                method = replica.handle_request
            # the wall stamp rides to the replica, which records its
            # time-in-queue (submit → execution start) against it
            ref = method.remote(args, kwargs, model_id, time.time())

        def _done() -> None:
            self._untrack_inflight(key)
            # one request_seconds observation per request, handle-side:
            # covers proxy AND direct-handle traffic without double
            # counting, and a request the proxy abandoned at its
            # deadline still records its true latency
            _telemetry.observe_request(self.deployment_name,
                                       time.monotonic() - t_submit)

        # completion observer — no extra thread, no second result fetch
        import ray_tpu._private.worker as worker_mod
        cw = worker_mod.global_worker().core_worker
        if stream:
            # account completion on the generator TASK's handle ref —
            # it fires when the replica finishes producing, whether or
            # not the caller ever iterates the response (an abandoned
            # stream must not inflate the replica's load counters)
            cw.add_done_callback(ref.handle, _done)
            return _StreamingResponse(ref)
        cw.add_done_callback(ref, _done)
        return ref

    def _submit_batch(self, items: List[Any]):
        """Proxy-coalesced submit: N single-positional requests as ONE
        handle_request_batch task (see proxy_fleet _Coalescer /
        Replica.handle_request_batch). Routed like any request (P2C);
        in-flight accounting counts the one task, request_seconds
        observes once per fused item on completion."""
        from ray_tpu._private import spans as _spans_lib
        from ray_tpu.serve import _telemetry
        t_submit = time.monotonic()
        n = len(items)
        with _spans_lib.span("serve.handle.submit",
                             deployment=self.deployment_name,
                             batch=n):
            self._refresh()
            replica = self._pick("")
            key = replica._actor_id.hex()
            self._track_inflight(key)
            ref = replica.handle_request_batch.remote(
                list(items), "", time.time())

        def _done() -> None:
            self._untrack_inflight(key)
            dur = time.monotonic() - t_submit
            for _ in range(n):
                _telemetry.observe_request(self.deployment_name, dur)

        import ray_tpu._private.worker as worker_mod
        cw = worker_mod.global_worker().core_worker
        cw.add_done_callback(ref, _done)
        return ref


def _listen_loop(handle_ref) -> None:
    """Long-poll loop for one DeploymentHandle (held by weakref): block
    in the controller until the deployment's snapshot advances, apply
    the pushed routing info, re-arm. Exits when the handle is collected
    or the cluster goes away repeatedly."""
    version = 0
    failures = 0
    while True:
        handle = handle_ref()
        if handle is None:
            return
        controller = handle._controller
        name = handle.deployment_name
        del handle  # don't pin the handle while parked in the long poll
        try:
            # server-side park (10s) stays well under the client timeout
            # (40s) so a call queued behind a full 'control' group still
            # returns in time instead of feeding the failure counter
            # long-poll: ONE in-flight call per loop turn is the design
            out = ray_tpu.get(  # graftlint: disable=RT002
                controller.listen_for_change.remote({name: version}, 10.0),
                timeout=40)
            failures = 0
        except Exception:  # noqa: BLE001 — controller gone/busy
            failures += 1
            if failures >= 5:
                # give up, but let a later _refresh re-arm a fresh
                # listener (e.g. after a controller restart)
                handle = handle_ref()
                if handle is not None:
                    handle._listener_started = False
                return
            time.sleep(1.0)
            continue
        if not out:
            continue  # timeout: re-arm
        handle = handle_ref()
        if handle is None:
            return
        version, info = out[name]
        handle._apply_routing_info(info)
        handle._last_refresh = time.monotonic()


class _HandleOptions:
    """Per-call view over a DeploymentHandle (reference handle
    .options(...))."""

    def __init__(self, handle: DeploymentHandle, model_id: str,
                 stream: bool):
        self._handle = handle
        self._model_id = model_id
        self._stream = stream

    def remote(self, *args: Any, **kwargs: Any):
        return self._handle._submit(args, kwargs,
                                    model_id=self._model_id,
                                    stream=self._stream)


class _StreamingResponse:
    """Iterates a streaming deployment call's chunks as values
    (reference serve streaming responses: the proxy iterates the
    ObjectRefGenerator and yields chunk bytes)."""

    def __init__(self, gen):
        self._gen = gen

    def __iter__(self):
        from ray_tpu._private.config import Config
        for ref in self._gen:
            # streaming: chunks are consumed in order as they land;
            # bounded per chunk — a wedged generator must fail the
            # consumer instead of parking it forever (RT017)
            yield ray_tpu.get(  # graftlint: disable=RT002
                ref, timeout=Config.serve_request_timeout_s)


def run(app: Any, *, name: Optional[str] = None) -> DeploymentHandle:
    """Deploy and wait ready (reference serve.run)."""
    if isinstance(app, Deployment):
        app = app.bind()
    d = app.deployment
    controller = _get_or_create_controller()
    import cloudpickle
    # proxy-side coalescing eligibility: a @serve.batch-decorated
    # __call__ means single-positional ingress requests can fuse into
    # one replica submit (proxy_fleet _Coalescer)
    coalesce = bool(getattr(
        getattr(d.func_or_class, "__call__", None),
        "_serve_batch", False))
    ray_tpu.get(controller.deploy.remote(
        name=name or d.name,
        target_blob=cloudpickle.dumps(d.func_or_class),
        init_args=app.init_args, init_kwargs=app.init_kwargs,
        num_replicas=d.num_replicas,
        max_concurrent_queries=d.max_concurrent_queries,
        ray_actor_options=d.ray_actor_options,
        autoscaling=d.autoscaling_config,
        max_queued_requests=d.max_queued_requests,
        rate_limit_rps=d.rate_limit_rps,
        coalesce=coalesce), timeout=300)
    return DeploymentHandle(name or d.name, controller)


def get_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


def delete(name: str) -> None:
    controller = _get_or_create_controller()
    ray_tpu.get(controller.delete_deployment.remote(name), timeout=120)


def shutdown() -> None:
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                       namespace=_NAMESPACE)
    except Exception:  # noqa: BLE001
        return
    try:
        ray_tpu.get(controller.shutdown.remote(), timeout=120)
    except Exception:  # noqa: BLE001 - wedged; the kill below is the backstop
        pass
    try:
        ray_tpu.kill(controller)
    except Exception:  # noqa: BLE001 - controller already dead
        pass


def _local_fleet_proxy(status: Dict[str, Any]) -> Any:
    """The calling node's proxy actor out of a fleet status (falls back
    to any healthy proxy — a driver on a proxyless node still gets an
    ingress handle)."""
    from ray_tpu.serve._private.proxy_fleet.fleet import (
        PROXY_NAME_PREFIX)
    my_node = ray_tpu.get_runtime_context().get_node_id()
    proxies = status.get("proxies", [])
    # prefer local, healthy, NOT-draining (a mid-roll fleet: the
    # draining proxy still serves, but its replacement is the one
    # whose port survives this round)
    ordered = sorted(proxies,
                     key=lambda p: (p["node_id"] != my_node,
                                    bool(p.get("draining", False)),
                                    not p.get("healthy", False)))
    for p in ordered:
        try:
            return ray_tpu.get_actor(
                f"{PROXY_NAME_PREFIX}{p['node_id'][:12]}",
                namespace=_NAMESPACE)
        except Exception:  # noqa: BLE001 - raced a dying proxy
            continue
    raise RuntimeError(f"ingress fleet started no proxies: {status}")


def start_http(port: int = 8000,
               request_timeout_s: Optional[float] = None) -> Any:
    """Start the ingress fleet's HTTP side (reference serve.start +
    proxy_state): ONE asyncio proxy per alive node, with admission
    control, load shedding, and drain-safe rolling updates (README
    "Serve at scale"). POST/GET /<deployment> with a JSON body calls
    the deployment and returns the JSON result; `request_timeout_s`
    bounds each request's handle wait (default
    Config.serve_request_timeout_s; timeouts surface as 504). To serve
    gRPC off the same per-node event loops, arm the fleet with
    serve.start_fleet(grpc_port=...); serve.start_grpc remains the
    LEGACY standalone gRPC actor.

    Returns the LOCAL node's proxy actor (API-compatible with the old
    single threading proxy: .ready / .stop / .requests_snapshot);
    fleet-wide state lives behind serve.fleet_status(). Config changes
    roll the fleet node-by-node (drain-first) on subsequent reconcile
    rounds. Proxies self-register as named actors
    (SERVE_PROXY_FLEET_<node>, namespace "serve") so the
    request-telemetry query plane can enumerate them."""
    controller = _get_or_create_controller()
    last: Optional[Exception] = None
    for _attempt in range(3):
        # bounded 3-attempt name-release retry, one call per attempt —
        # not a serialization of independent work
        status = ray_tpu.get(  # graftlint: disable=RT002
            controller.start_proxy_fleet.remote(
                http_port=port, request_timeout_s=request_timeout_s),
            timeout=120)
        try:
            return _local_fleet_proxy(status)
        except RuntimeError as e:
            # a just-killed predecessor can hold the actor name for a
            # beat; the next reconcile round starts the replacement
            last = e
            time.sleep(1.0)
    raise last


def start_fleet(http_port: Optional[int] = None,
                grpc_port: Optional[int] = None,
                request_timeout_s: Optional[float] = None
                ) -> Dict[str, Any]:
    """Arm (or reconfigure) the whole ingress fleet explicitly — the
    superset of start_http that also serves gRPC from each node's
    event loop (`grpc_port`; shed → RESOURCE_EXHAUSTED with a
    retry-after metadata hint). Every parameter is keep-if-None, so
    `serve.start_fleet(grpc_port=9001)` adds gRPC WITHOUT rolling the
    armed HTTP port. Returns the fleet status (per-node proxies with
    bound ports). A changed config rolls proxies node-by-node,
    drain-first."""
    controller = _get_or_create_controller()
    return ray_tpu.get(controller.start_proxy_fleet.remote(
        http_port=http_port, grpc_port=grpc_port,
        request_timeout_s=request_timeout_s), timeout=120)


def fleet_status() -> Dict[str, Any]:
    """Ingress fleet state: per-node proxies, ports, health, drain
    flags (CLI: `ray_tpu serve fleet`)."""
    controller = _get_or_create_controller()
    return ray_tpu.get(controller.fleet_status.remote(), timeout=30)


def drain_proxy(node_id: str) -> bool:
    """Drain one node's ingress proxy (stop accepting → finish
    in-flight → deregister) ahead of node removal. Returns False if the
    node has no proxy."""
    controller = _get_or_create_controller()
    return ray_tpu.get(controller.drain_proxy.remote(node_id),
                       timeout=120)
