"""gRPC ingress for Serve deployments.

reference parity: serve/_private/proxy.py:556 (gRPCProxy) — the
reference runs an HTTP proxy AND a gRPC proxy per node; its gRPC proxy
dispatches user-registered servicer methods to deployment handles. Here
the service is generic (grpc.GenericRpcHandler — no protoc step): the
method path selects the deployment (`/ray_tpu.serve/<deployment>`), the
request payload is a pickled (args, kwargs) tuple, and the response is
the pickled result; `grpc_call` is the matching client helper. Routing
reuses DeploymentHandle (queue-aware P2C + long-poll push), exactly as
the reference's proxies route through handles.

Request telemetry mirrors the HTTP proxy (README "Serve request
telemetry"): the ``x-request-id`` invocation-metadata entry is honored
(minted otherwise) and echoed back in the trailing metadata, spans +
RED metrics record each hop, and the per-proxy ring captures slow and
errored requests. Error semantics: unknown deployment → NOT_FOUND,
handle timeout (`serve_request_timeout_s`, bounded by the client
deadline) → DEADLINE_EXCEEDED, anything else → INTERNAL.
"""

from __future__ import annotations

import pickle
import threading
from time import perf_counter
from typing import Any, Dict, Optional

SERVICE_PREFIX = "/ray_tpu.serve/"

# gRPC status → the HTTP-ish code the RED counter + request ring use,
# so `ray_tpu serve requests` reads uniformly across both ingresses.
_CODE_OK = 200
_CODE_NOT_FOUND = 404
_CODE_INTERNAL = 500
_CODE_TIMEOUT = 504


class GRPCProxyActor:
    """Per-node gRPC ingress actor (start with serve.start_grpc)."""

    def __init__(self, port: int = 9000, max_workers: int = 16,
                 request_timeout_s: Optional[float] = None):
        from concurrent import futures

        import grpc

        from ray_tpu._private.config import Config
        from ray_tpu.serve import _telemetry

        self._handles: Dict[str, Any] = {}
        self._handles_lock = threading.Lock()
        self._timeout = float(request_timeout_s
                              if request_timeout_s is not None
                              else Config.serve_request_timeout_s)
        self._ring = _telemetry.RequestRing()
        proxy = self

        class _Generic(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                method = handler_call_details.method
                if not method.startswith(SERVICE_PREFIX):
                    return None
                name = method[len(SERVICE_PREFIX):]

                def unary(request: bytes, context):
                    import ray_tpu
                    from ray_tpu._private import spans as spans_lib
                    from ray_tpu.serve import _telemetry
                    from ray_tpu.serve.api import DeploymentNotFound
                    from ray_tpu.util import tracing
                    meta = dict(context.invocation_metadata() or ())
                    trace_id = _telemetry.ingress_trace_id(
                        meta.get("x-request-id"))
                    context.set_trailing_metadata(
                        (("x-request-id", trace_id),))
                    # bound by the CLIENT's deadline so abandoned
                    # calls release their worker thread instead of
                    # blocking the bounded executor for the full
                    # configured timeout
                    remaining = context.time_remaining()
                    timeout = min(proxy._timeout, remaining) \
                        if remaining is not None else proxy._timeout
                    t_start = perf_counter()
                    stages: Dict[str, float] = {}
                    code, err, status = _CODE_OK, None, None
                    out = b""
                    with tracing.use_trace(trace_id):
                        with spans_lib.span("serve.proxy.request",
                                            deployment=name,
                                            transport="grpc") as sp:
                            try:
                                out = proxy._dispatch(
                                    name, request, timeout, stages)
                            except DeploymentNotFound as e:
                                code, err = _CODE_NOT_FOUND, str(e)
                                status = grpc.StatusCode.NOT_FOUND
                                # don't let a path scan grow the
                                # handle cache one entry per bogus
                                # name forever
                                with proxy._handles_lock:
                                    proxy._handles.pop(name, None)
                            except ray_tpu.exceptions.GetTimeoutError:
                                # may be the handle's internal routing
                                # fetch timing out — report elapsed
                                # time, not the configured budget
                                code = _CODE_TIMEOUT
                                err = (f"deployment {name!r} did not "
                                       f"respond within "
                                       f"{perf_counter() - t_start:.1f}"
                                       f"s (request timeout "
                                       f"{timeout:g}s)")
                                status = \
                                    grpc.StatusCode.DEADLINE_EXCEEDED
                            except Exception as e:  # noqa: BLE001
                                code, err = _CODE_INTERNAL, str(e)
                                status = grpc.StatusCode.INTERNAL
                            sp["code"] = code
                    _telemetry.record_ingress(
                        proxy._ring, deployment=name, method="grpc",
                        code=code, trace_id=trace_id,
                        total_s=perf_counter() - t_start,
                        stages=stages, error=err)
                    if err is not None:
                        context.abort(status, err)
                    return out

                return grpc.unary_unary_rpc_method_handler(
                    unary,
                    request_deserializer=None,   # raw bytes in
                    response_serializer=None)    # raw bytes out

        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=[("grpc.max_receive_message_length", -1),
                     ("grpc.max_send_message_length", -1)])
        self._server.add_generic_rpc_handlers((_Generic(),))
        self.port = self._server.add_insecure_port(f"127.0.0.1:{port}")
        if self.port == 0:
            # grpc reports bind failure by returning port 0, not raising
            raise OSError(f"gRPC proxy could not bind 127.0.0.1:{port}")
        self._server.start()

    def _dispatch(self, name: str, request: bytes, timeout: float,
                  stages: Optional[Dict[str, float]] = None) -> bytes:
        import ray_tpu
        from ray_tpu.serve.api import DeploymentHandle

        stages = stages if stages is not None else {}
        t0 = perf_counter()
        with self._handles_lock:
            handle = self._handles.get(name)
            if handle is None:
                handle = DeploymentHandle(name)
                self._handles[name] = handle
        args, kwargs = pickle.loads(request) if request else ((), {})
        stages["parse_s"] = perf_counter() - t0
        t0 = perf_counter()
        ref = handle.remote(*args, **kwargs)
        stages["route_s"] = perf_counter() - t0
        t0 = perf_counter()
        result = ray_tpu.get(ref, timeout=timeout)
        stages["handle_s"] = perf_counter() - t0
        t0 = perf_counter()
        out = pickle.dumps(result, protocol=5)
        stages["serialize_s"] = perf_counter() - t0
        return out

    def ready(self) -> int:
        return self.port

    def requests_snapshot(self, deployment: Optional[str] = None,
                          errors: bool = False,
                          slowest: Optional[int] = None):
        """Captured slow/errored requests (see _telemetry.RequestRing)
        — queried by util.state.serve_requests() across all proxies."""
        return self._ring.snapshot(deployment=deployment, errors=errors,
                                   slowest=slowest)

    def stop(self) -> None:
        # stop() is async in grpc: wait the returned event so callers
        # can rebind the port immediately after this returns (the HTTP
        # proxy's shutdown() blocks the same way)
        self._server.stop(grace=1.0).wait()


def start_grpc(port: int = 9000,
               request_timeout_s: Optional[float] = None):
    """Start the gRPC ingress actor (reference serve start with
    gRPC options); returns its handle (.ready.remote() -> bound port).
    The actor gets a unique cluster name (SERVE_PROXY_GRPC_*, namespace
    "serve") so the request-telemetry query plane can enumerate it."""
    import uuid as _uuid

    import ray_tpu
    cls = ray_tpu.remote(GRPCProxyActor)
    proxy = cls.options(
        num_cpus=0.1, max_concurrency=8,
        name=f"SERVE_PROXY_GRPC_{_uuid.uuid4().hex[:8]}",
        namespace="serve").remote(port, request_timeout_s=request_timeout_s)
    ray_tpu.get(proxy.ready.remote(), timeout=60)
    return proxy


def grpc_call(address: str, deployment: str, *args: Any,
              timeout: float = 120.0, request_id: Optional[str] = None,
              **kwargs: Any) -> Any:
    """Client helper: call `deployment` through a gRPC proxy at
    `address` ("host:port"). `request_id` rides the x-request-id
    metadata and becomes the request's trace id end to end."""
    import grpc

    with grpc.insecure_channel(
            address,
            options=[("grpc.max_receive_message_length", -1),
                     ("grpc.max_send_message_length", -1)]) as channel:
        fn = channel.unary_unary(
            SERVICE_PREFIX + deployment,
            request_serializer=None,
            response_deserializer=None)
        payload = pickle.dumps((args, kwargs), protocol=5)
        metadata = (("x-request-id", request_id),) if request_id else None
        return pickle.loads(fn(payload, timeout=timeout,
                               metadata=metadata))
