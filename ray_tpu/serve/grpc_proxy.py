"""gRPC ingress for Serve deployments.

reference parity: serve/_private/proxy.py:556 (gRPCProxy) — the
reference runs an HTTP proxy AND a gRPC proxy per node; its gRPC proxy
dispatches user-registered servicer methods to deployment handles. Here
the service is generic (grpc.GenericRpcHandler — no protoc step): the
method path selects the deployment (`/ray_tpu.serve/<deployment>`), the
request payload is a pickled (args, kwargs) tuple, and the response is
the pickled result; `grpc_call` is the matching client helper. Routing
reuses DeploymentHandle (queue-aware P2C + long-poll push), exactly as
the reference's proxies route through handles.
"""

from __future__ import annotations

import pickle
import threading
from typing import Any, Dict

SERVICE_PREFIX = "/ray_tpu.serve/"


class GRPCProxyActor:
    """Per-node gRPC ingress actor (start with serve.start_grpc)."""

    def __init__(self, port: int = 9000, max_workers: int = 16):
        from concurrent import futures

        import grpc

        self._handles: Dict[str, Any] = {}
        self._handles_lock = threading.Lock()
        proxy = self

        class _Generic(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                method = handler_call_details.method
                if not method.startswith(SERVICE_PREFIX):
                    return None
                name = method[len(SERVICE_PREFIX):]

                def unary(request: bytes, context):
                    try:
                        # bound by the CLIENT's deadline so abandoned
                        # calls release their worker thread instead of
                        # blocking the bounded executor for 120s
                        remaining = context.time_remaining()
                        timeout = min(120.0, remaining) \
                            if remaining is not None else 120.0
                        return proxy._dispatch(name, request, timeout)
                    except Exception as e:  # noqa: BLE001
                        context.abort(grpc.StatusCode.INTERNAL, str(e))

                return grpc.unary_unary_rpc_method_handler(
                    unary,
                    request_deserializer=None,   # raw bytes in
                    response_serializer=None)    # raw bytes out

        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=[("grpc.max_receive_message_length", -1),
                     ("grpc.max_send_message_length", -1)])
        self._server.add_generic_rpc_handlers((_Generic(),))
        self.port = self._server.add_insecure_port(f"127.0.0.1:{port}")
        if self.port == 0:
            # grpc reports bind failure by returning port 0, not raising
            raise OSError(f"gRPC proxy could not bind 127.0.0.1:{port}")
        self._server.start()

    def _dispatch(self, name: str, request: bytes,
                  timeout: float = 120.0) -> bytes:
        import ray_tpu
        from ray_tpu.serve.api import DeploymentHandle

        with self._handles_lock:
            handle = self._handles.get(name)
            if handle is None:
                handle = DeploymentHandle(name)
                self._handles[name] = handle
        args, kwargs = pickle.loads(request) if request else ((), {})
        result = ray_tpu.get(handle.remote(*args, **kwargs),
                             timeout=timeout)
        return pickle.dumps(result, protocol=5)

    def ready(self) -> int:
        return self.port

    def stop(self) -> None:
        # stop() is async in grpc: wait the returned event so callers
        # can rebind the port immediately after this returns (the HTTP
        # proxy's shutdown() blocks the same way)
        self._server.stop(grace=1.0).wait()


def start_grpc(port: int = 9000):
    """Start the gRPC ingress actor (reference serve start with
    gRPC options); returns its handle (.ready.remote() -> bound port)."""
    import ray_tpu
    cls = ray_tpu.remote(GRPCProxyActor)
    proxy = cls.options(num_cpus=0.1, max_concurrency=8).remote(port)
    ray_tpu.get(proxy.ready.remote(), timeout=60)
    return proxy


def grpc_call(address: str, deployment: str, *args: Any,
              timeout: float = 120.0, **kwargs: Any) -> Any:
    """Client helper: call `deployment` through a gRPC proxy at
    `address` ("host:port")."""
    import grpc

    with grpc.insecure_channel(
            address,
            options=[("grpc.max_receive_message_length", -1),
                     ("grpc.max_send_message_length", -1)]) as channel:
        fn = channel.unary_unary(
            SERVICE_PREFIX + deployment,
            request_serializer=None,
            response_deserializer=None)
        payload = pickle.dumps((args, kwargs), protocol=5)
        return pickle.loads(fn(payload, timeout=timeout))
