"""ray_tpu.serve: online model serving (Serve equivalent).

reference parity: python/ray/serve — deployments reconciled by a
controller actor (serve/_private/controller.py:87, deployment_state
.py:1149), power-of-two-choices routing (router.py:290,893), per-node
HTTP ingress (proxy.py:122), queue-depth autoscaling
(autoscaling_policy.py). Scaled to this runtime: one controller actor,
replica actors with in-flight accounting, and a per-node asyncio
ingress fleet (serve/_private/proxy_fleet/) with admission control,
load shedding, and drain-safe rolling updates (README "Serve at
scale"). The old threading HTTP proxy survives as a compat shim in
serve/proxy.py.
"""

from ray_tpu.serve.api import (Application, Deployment,  # noqa: F401
                               DeploymentHandle, DeploymentNotFound,
                               delete, deployment, drain_proxy,
                               fleet_status, get_handle, run,
                               shutdown, start_fleet, start_http)
from ray_tpu.serve.batching import batch  # noqa: F401
from ray_tpu.serve.controller import (get_multiplexed_model_id,  # noqa: F401
                                      multiplexed)
from ray_tpu.serve.grpc_proxy import grpc_call, start_grpc  # noqa: F401

__all__ = [
    "deployment", "Deployment", "Application", "DeploymentHandle",
    "DeploymentNotFound",
    "run", "get_handle", "delete", "shutdown", "start_http",
    "start_grpc", "grpc_call", "batch",
    "start_fleet", "fleet_status", "drain_proxy",
    "multiplexed", "get_multiplexed_model_id",
]

from ray_tpu._private.usage import record_library_usage as _rlu
_rlu('serve')
del _rlu
