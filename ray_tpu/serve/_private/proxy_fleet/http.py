"""Minimal asyncio HTTP/1.1 server for the ingress fleet.

The stdlib ships no asyncio HTTP server and the image bakes no
uvicorn/aiohttp, so the fleet carries its own ~150-line HTTP/1.1
subset: request line + headers, Content-Length bodies, keep-alive
(the throughput path — a closed-loop client reuses its connection for
every request), and streaming writes. Exactly what the ingress needs,
nothing more; TLS/chunked-upload/pipelining are out of scope.

Zero-copy streaming: `Response.body` may be bytes OR a memoryview —
large `bytes` deployment results come out of `ray_tpu.get` as views
backed by the PR-3 store envelope (leased, no copy), and `write_to`
slices them straight into `transport.write` in bounded chunks with
back-pressure (`await drain()`) between chunks, so a multi-MB payload
streams without ever being copied into a Python-level response
buffer.
"""

from __future__ import annotations

import asyncio
import logging
import time as _time
from typing import Any, Awaitable, Callable, Dict, Optional

logger = logging.getLogger(__name__)

MAX_HEADER_BYTES = 64 * 1024
MAX_BODY_BYTES = 512 * 1024 * 1024
STREAM_CHUNK = 256 * 1024

REASONS = {200: "OK", 400: "Bad Request", 404: "Not Found",
           405: "Method Not Allowed", 413: "Payload Too Large",
           500: "Internal Server Error", 503: "Service Unavailable",
           504: "Gateway Timeout", 499: "Client Closed Request"}


class BadRequest(Exception):
    pass


class Request:
    __slots__ = ("method", "path", "headers", "body")

    def __init__(self, method: str, path: str,
                 headers: Dict[str, str], body: bytes):
        self.method = method
        self.path = path
        self.headers = headers  # keys lower-cased
        self.body = body


class Response:
    """status + headers + body (bytes or memoryview for zero-copy
    streaming). Content-Length is always set; the connection stays
    keep-alive unless `close` is set. `on_written(nbytes, write_s,
    error)` — when set — fires after the write attempt (telemetry must
    record write time AND write failures, and only once the entry is
    complete)."""

    __slots__ = ("status", "headers", "body", "close", "on_written")

    def __init__(self, status: int, body: Any = b"",
                 headers: Optional[Dict[str, str]] = None,
                 close: bool = False):
        self.status = status
        self.headers = headers or {}
        self.body = body
        self.close = close
        self.on_written: Optional[Callable] = None

    async def write_to(self, writer: asyncio.StreamWriter) -> int:
        body = self.body
        view = memoryview(body) if not isinstance(body, memoryview) \
            else body
        head = [f"HTTP/1.1 {self.status} "
                f"{REASONS.get(self.status, 'Unknown')}"]
        hdrs = dict(self.headers)
        hdrs.setdefault("Content-Type", "application/json")
        hdrs["Content-Length"] = str(view.nbytes)
        hdrs["Connection"] = "close" if self.close else "keep-alive"
        for k, v in hdrs.items():
            head.append(f"{k}: {v}")
        head.append("\r\n")
        writer.write("\r\n".join(head).encode("latin-1"))
        # bounded chunks with drain between them: back-pressure from a
        # slow client pauses THIS response coroutine, never the loop
        for off in range(0, view.nbytes, STREAM_CHUNK):
            writer.write(view[off:off + STREAM_CHUNK])
            await writer.drain()
        if view.nbytes == 0:
            await writer.drain()
        return view.nbytes


async def read_request(reader: asyncio.StreamReader
                       ) -> Optional[Request]:
    """One request off a keep-alive connection; None on clean EOF
    (client closed between requests). Raises BadRequest on garbage."""
    try:
        head = await reader.readuntil(b"\r\n\r\n")
    except asyncio.IncompleteReadError as e:
        if not e.partial:
            return None  # clean close between requests
        raise BadRequest("truncated request head") from e
    except asyncio.LimitOverrunError as e:
        raise BadRequest("oversized request head") from e
    if len(head) > MAX_HEADER_BYTES:
        raise BadRequest("oversized request head")
    try:
        lines = head.decode("latin-1").split("\r\n")
        method, path, _version = lines[0].split(" ", 2)
    except ValueError as e:
        raise BadRequest(f"malformed request line {lines[0]!r}") from e
    headers: Dict[str, str] = {}
    for line in lines[1:]:
        if not line:
            continue
        k, _sep, v = line.partition(":")
        headers[k.strip().lower()] = v.strip()
    try:
        n = int(headers.get("content-length", 0))
    except ValueError as e:
        raise BadRequest("bad Content-Length") from e
    if n < 0:
        raise BadRequest("negative Content-Length")
    if n > MAX_BODY_BYTES:
        raise BadRequest(f"body of {n} bytes over the "
                         f"{MAX_BODY_BYTES}-byte cap")
    body = await reader.readexactly(n) if n else b""
    return Request(method.upper(), path, headers, body)


Handler = Callable[[Request], Awaitable[Response]]


class HTTPServer:
    """asyncio HTTP/1.1 server dispatching every request to one async
    handler. `drain()` stops accepting new connections, lets in-flight
    requests finish (keep-alive connections get `Connection: close` on
    their final response), and resolves when the last one is done."""

    def __init__(self, handler: Handler, host: str = "127.0.0.1"):
        self._handler = handler
        self._host = host
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()
        self._inflight = 0
        self._draining = False
        self._idle = asyncio.Event()
        self._idle.set()
        self.port = 0

    async def start(self, port: int) -> int:
        self._server = await asyncio.start_server(
            self._serve_conn, self._host, port,
            limit=MAX_HEADER_BYTES)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def inflight(self) -> int:
        return self._inflight

    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        self._conns.add(writer)
        try:
            while True:
                try:
                    req = await read_request(reader)
                except BadRequest as e:
                    await Response(
                        400, b'{"error": "' +
                        str(e).replace('"', "'").encode() + b'"}',
                        close=True).write_to(writer)
                    return
                if req is None:
                    return
                # in-flight covers handler AND response write: a drain
                # that resolved mid-write would let stop() truncate a
                # response that was already streaming to the client
                self._inflight += 1
                self._idle.clear()
                try:
                    resp = await self._handler(req)
                    if self._draining:
                        # each connection serves out the request it
                        # already carried, then closes: clients
                        # reconnect and land on the replacement proxy
                        # (drain never hangs on a chatty client)
                        resp.close = True
                    t0 = _time.perf_counter()
                    nbytes, write_err = 0, None
                    try:
                        nbytes = await resp.write_to(writer)
                    except (ConnectionError,
                            asyncio.CancelledError) as e:
                        write_err = str(e) or type(e).__name__
                        raise
                    finally:
                        if resp.on_written is not None:
                            try:
                                resp.on_written(
                                    nbytes,
                                    _time.perf_counter() - t0,
                                    write_err)
                            except Exception:  # noqa: BLE001 -
                                # telemetry must never kill the conn
                                logger.exception(
                                    "on_written callback failed")
                finally:
                    self._inflight -= 1
                    if self._inflight == 0:
                        self._idle.set()
                if resp.close:
                    return
        except (ConnectionError, asyncio.IncompleteReadError):
            pass  # client went away; per-request accounting already done
        finally:
            self._conns.discard(writer)
            try:
                writer.close()
            except Exception:  # noqa: BLE001 - transport already gone
                pass

    async def drain(self, timeout_s: float) -> bool:
        """Stop accepting, finish in-flight; True if fully drained
        within `timeout_s`."""
        self._draining = True
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        try:
            await asyncio.wait_for(self._idle.wait(), timeout_s)
            return True
        except asyncio.TimeoutError:
            return False

    async def stop(self) -> None:
        if not self._draining and self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for w in list(self._conns):
            try:
                w.close()
            except Exception:  # noqa: BLE001 - transport already gone
                pass
        self._conns.clear()
