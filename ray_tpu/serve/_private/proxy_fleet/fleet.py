"""ProxyFleetManager: one asyncio ingress proxy per alive node.

reference parity: serve/_private/proxy_state.py (ProxyStateManager):
the controller reconciles the proxy fleet exactly like it reconciles
replicas — one proxy per alive node (NodeAffinity-pinned), periodic
health checks, replacements for dead proxies, and a drain lifecycle
(stop accepting → finish in-flight → deregister → stop) for rolling
updates and node removal.

Runs INSIDE the ServeController actor (its reconcile loop calls
`reconcile()` each period); all state is controller-local, published
to callers via `status()` / the routing long-poll.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

PROXY_NAME_PREFIX = "SERVE_PROXY_FLEET_"
_NAMESPACE = "serve"


@dataclass
class _ProxyState:
    node_id: str
    actor: Any
    http_port: int = 0
    grpc_port: Optional[int] = None
    healthy: bool = False
    consecutive_failures: int = 0
    draining: bool = False
    started_at: float = field(default_factory=time.monotonic)


class ProxyFleetManager:
    """Controller-side fleet reconciliation. Thread-safe for the
    controller's RPC threads + reconcile thread."""

    # consecutive failed pings before a proxy is declared dead and
    # replaced (mirrors gcs health_check_failure_threshold: one slow
    # ping on a loaded box must not churn the ingress)
    FAILURE_THRESHOLD = 3
    PING_TIMEOUT_S = 10.0

    def __init__(self) -> None:
        self._proxies: Dict[str, _ProxyState] = {}
        self._lock = threading.Lock()
        # serializes whole reconcile rounds: the fleet loop and a
        # synchronous start_proxy_fleet call must not race a node's
        # proxy creation (the actor name would bounce via adopt paths)
        self._round_lock = threading.Lock()
        self._enabled = False
        self._http_port = 0
        self._grpc_port: Optional[int] = None
        self._request_timeout_s: Optional[float] = None
        self._version = 0  # bumped on every fleet config change
        # operator-drained nodes (pending removal): reconcile must not
        # resurrect their proxies; cleared by the next ensure()
        self._cordoned: set = set()
        # proxy-start backoff: node_id -> (consecutive failures,
        # monotonic next-retry). A node that can't host a proxy (e.g.
        # fixed port already bound on a shared-host test cluster) must
        # not churn an actor spawn + stack trace every 1s round.
        self._start_backoff: Dict[str, tuple] = {}

    # ---- config -----------------------------------------------------

    def ensure(self, *, http_port: Optional[int] = None,
               grpc_port: Optional[int] = None,
               request_timeout_s: Optional[float] = None) -> None:
        """Turn the fleet on (idempotent). A CHANGED config (new ports
        or timeout) rolls the fleet: each node's proxy is drained and
        replaced on the next reconcile rounds. Every parameter is
        keep-if-None, so arming one knob (say grpc) never rolls the
        others onto new values."""
        with self._lock:
            # compare EFFECTIVE config (defaulted args keep the stored
            # value): serve.start_fleet(grpc_port=9001) after
            # serve.start_http(8000) must not roll HTTP off :8000
            http_keep = self._http_port if http_port is None else \
                http_port
            grpc_keep = self._grpc_port if grpc_port is None else \
                grpc_port
            timeout_keep = self._request_timeout_s \
                if request_timeout_s is None else request_timeout_s
            changed = (self._enabled
                       and (http_keep != self._http_port
                            or grpc_keep != self._grpc_port
                            or timeout_keep != self._request_timeout_s))
            self._enabled = True
            self._http_port = http_keep
            self._grpc_port = grpc_keep
            self._request_timeout_s = timeout_keep
            self._cordoned.clear()  # re-arming lifts node cordons
            if changed:
                self._version += 1
                for st in self._proxies.values():
                    st.draining = True  # rolled on upcoming rounds

    # ---- queries ----------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "enabled": self._enabled,
                "version": self._version,
                "http_port": self._http_port,
                "grpc_port": self._grpc_port,
                "proxies": [
                    {"node_id": nid, "http_port": st.http_port,
                     "grpc_port": st.grpc_port, "healthy": st.healthy,
                     "draining": st.draining,
                     "consecutive_failures": st.consecutive_failures}
                    for nid, st in self._proxies.items()],
            }

    # ---- lifecycle --------------------------------------------------

    def _start_proxy(self, node_id: str,
                     allow_adopt: bool = True) -> Optional[_ProxyState]:
        import ray_tpu
        from ray_tpu.serve._private.proxy_fleet.proxy import (
            AsyncProxyActor)
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy)
        cls = ray_tpu.remote(AsyncProxyActor)
        name = f"{PROXY_NAME_PREFIX}{node_id[:12]}"
        try:
            actor = cls.options(
                num_cpus=0.05, max_concurrency=4,
                concurrency_groups={"control": 4},
                name=name, namespace=_NAMESPACE,
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id=node_id, soft=False)).remote(
                http_port=self._http_port, grpc_port=self._grpc_port,
                request_timeout_s=self._request_timeout_s,
                node_id=node_id)
        except ValueError:
            # name taken: a previous-generation proxy is still
            # registered (e.g. user killed the controller mid-roll).
            # The rolling path passes allow_adopt=False — adopting the
            # predecessor it JUST stopped would register a dead
            # listener as healthy; the next round creates cleanly.
            if not allow_adopt:
                return None
            try:
                actor = ray_tpu.get_actor(name, namespace=_NAMESPACE)
                # a stopped/draining predecessor is no adoption target.
                # _round_lock is singleflight BY DESIGN: a whole fleet
                # round (blocking health checks included) must finish
                # before the next begins; only the two reconcile entry
                # points ever contend
                if ray_tpu.get(  # graftlint: disable=RT015
                        actor.ping.remote(),
                        timeout=self.PING_TIMEOUT_S) != "pong":
                    ray_tpu.kill(actor)
                    return None
                # config check: a live registered predecessor may be a
                # condemned zombie (user-killed, the kill not yet
                # delivered) from an OLDER fleet generation — adopting
                # it would serve stale ports/timeouts under the new
                # config. Mismatch → replace, same as a dead ping.
                armed = ray_tpu.get(  # graftlint: disable=RT015
                    actor.armed_config.remote(),
                    timeout=self.PING_TIMEOUT_S)
                if armed != {"http_port": self._http_port,
                             "grpc_port": self._grpc_port,
                             "request_timeout_s":
                                 self._request_timeout_s}:
                    ray_tpu.kill(actor)
                    return None
            except Exception:  # noqa: BLE001 - raced a dying actor
                return None
        except Exception:  # noqa: BLE001 — node vanished mid-start;
            logger.exception("proxy start failed on %s", node_id[:12])
            return None
        st = _ProxyState(node_id=node_id, actor=actor)
        try:
            # singleflight round lock by design (see adopt note above)
            ports = ray_tpu.get(  # graftlint: disable=RT015
                actor.ports.remote(), timeout=60)
            st.http_port = ports["http"]
            st.grpc_port = ports["grpc"]
            st.healthy = True
        except Exception:  # noqa: BLE001 — bind failure / node died:
            # reconcile retries next round
            logger.exception("proxy on %s failed readiness",
                             node_id[:12])
            try:
                ray_tpu.kill(actor)
            except Exception:  # noqa: BLE001 - already dead
                pass
            return None
        logger.info("serve fleet: proxy up on node %s (http:%d, "
                    "request timeout %ss)", node_id[:12], st.http_port,
                    self._request_timeout_s)
        return st

    def _drain_and_stop(self, st: _ProxyState) -> None:
        """Graceful removal: drain (stop accepting, finish in-flight),
        then stop + kill. Runs on the reconcile thread."""
        import ray_tpu
        from ray_tpu._private.config import Config
        try:
            ray_tpu.get(st.actor.drain.remote(),
                        timeout=Config.serve_drain_timeout_s + 15)
        except Exception:  # noqa: BLE001 — already dead / wedged: the
            pass           # kill below is the backstop
        try:
            ray_tpu.get(st.actor.stop.remote(), timeout=15)
        except Exception:  # noqa: BLE001 - stop is best-effort
            pass
        try:
            ray_tpu.kill(st.actor)
        except Exception:  # noqa: BLE001 - already dead
            pass

    def reconcile(self, alive_node_ids: List[str]) -> None:
        """One fleet round: start proxies for uncovered alive nodes,
        drop proxies for dead nodes, health-check the rest, roll
        draining proxies. At most ONE drain-replace per round so a
        config change rolls node-by-node (capacity stays up). Rounds
        are serialized (fleet loop vs synchronous start_proxy_fleet)."""
        with self._round_lock:
            self._reconcile_round(alive_node_ids)

    def _reconcile_round(self, alive_node_ids: List[str]) -> None:
        alive = set(alive_node_ids)
        with self._lock:
            if not self._enabled:
                return
            alive -= self._cordoned  # drained-for-removal stays down
            known = dict(self._proxies)
        # dead nodes: deregister (the actor died with its node)
        for nid in list(known):
            if nid not in alive:
                with self._lock:
                    st = self._proxies.pop(nid, None)
                if st is not None:
                    logger.info("serve fleet: node %s gone, proxy "
                                "deregistered", nid[:12])
                known.pop(nid, None)
        # health checks + at most one rolling replacement per round
        rolled = False
        for nid, st in known.items():
            if st.draining and not rolled:
                rolled = True
                self._drain_and_stop(st)
                with self._lock:
                    self._proxies.pop(nid, None)
                # no adoption here: the name may still be held by the
                # predecessor we just killed — better one round with
                # no proxy than a registered-dead one
                replacement = self._start_proxy(nid, allow_adopt=False)
                if replacement is not None:
                    with self._lock:
                        self._proxies[nid] = replacement
                continue
            health = self._ping(st)
            if health != "ok":
                st.consecutive_failures += (
                    self.FAILURE_THRESHOLD if health == "dead" else 1)
                if st.consecutive_failures >= self.FAILURE_THRESHOLD:
                    logger.warning(
                        "serve fleet: proxy on %s failed %d health "
                        "checks — replacing", nid[:12],
                        st.consecutive_failures)
                    with self._lock:
                        self._proxies.pop(nid, None)
                    try:
                        import ray_tpu
                        ray_tpu.kill(st.actor)
                    except Exception:  # noqa: BLE001 - already dead
                        pass
                    replacement = self._start_proxy(nid)
                    if replacement is not None:
                        with self._lock:
                            self._proxies[nid] = replacement
                else:
                    st.healthy = False
            else:
                st.healthy = True
                st.consecutive_failures = 0
        # uncovered alive nodes (exponential start backoff: a node
        # that can't host a proxy — fixed port already bound on a
        # shared-host cluster — retries at 2s/4s/.../30s, not every
        # round)
        now = time.monotonic()
        for nid in alive - set(known):
            failures, next_retry = self._start_backoff.get(nid, (0, 0.0))
            if now < next_retry:
                continue
            st = self._start_proxy(nid)
            if st is not None:
                self._start_backoff.pop(nid, None)
                with self._lock:
                    self._proxies[nid] = st
            else:
                failures += 1
                self._start_backoff[nid] = (
                    failures, now + min(30.0, 2.0 ** failures))
                if failures == 1:
                    logger.warning(
                        "serve fleet: proxy start failed on %s — "
                        "backing off (see exception above)", nid[:12])
        # backoff records for departed nodes must not accumulate
        self._start_backoff = {k: v for k, v in
                               self._start_backoff.items() if k in alive}

    def _ping(self, st: _ProxyState) -> str:
        """'ok' | 'slow' (counts toward the failure threshold) |
        'dead' (actor is gone — replaced immediately; a user-killed or
        node-crashed proxy must not ride out three rounds of grace)."""
        import ray_tpu
        try:
            # singleflight round lock by design (_reconcile_round)
            ray_tpu.get(  # graftlint: disable=RT015
                st.actor.ping.remote(), timeout=self.PING_TIMEOUT_S)
            return "ok"
        except (ray_tpu.exceptions.RayActorError,
                ray_tpu.exceptions.WorkerCrashedError):
            return "dead"
        except Exception:  # noqa: BLE001 — slow/timeout: grace applies
            return "slow"

    def drain_node(self, node_id: str) -> bool:
        """Operator-initiated drain of one node's proxy (node removal
        path): drain + stop + deregister WITHOUT replacement."""
        with self._lock:
            st = self._proxies.pop(node_id, None)
            self._cordoned.add(node_id)
        if st is None:
            return False
        self._drain_and_stop(st)
        return True

    def stop_all(self) -> None:
        with self._lock:
            states = list(self._proxies.values())
            self._proxies.clear()
            self._enabled = False
        for st in states:
            self._drain_and_stop(st)
