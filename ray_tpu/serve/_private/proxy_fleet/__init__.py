"""Serve ingress fleet: per-node asyncio proxies + admission control.

reference parity: serve/_private/proxy.py (one asyncio HTTP+gRPC proxy
per node) + proxy_state.py (controller-side fleet lifecycle: start one
proxy per alive node, health-check, drain before removal).

Layout:
  async_bridge.py  ObjectRef -> asyncio.Future bridge (no per-request
                   threads; the core worker's done callback wakes the
                   event loop)
  admission.py     per-deployment inflight/queue limits, token-bucket
                   rate limits, shed decisions (503 + Retry-After /
                   RESOURCE_EXHAUSTED)
  http.py          minimal asyncio HTTP/1.1 server (keep-alive,
                   zero-copy streaming writes for bytes payloads)
  proxy.py         AsyncProxyActor: HTTP + gRPC from one event loop,
                   drain lifecycle, request coalescing into
                   @serve.batch deployments
  fleet.py         ProxyFleetManager: controller-side reconciliation
                   (node join/death, health checks, rolling updates)
"""

from ray_tpu.serve._private.proxy_fleet.admission import (  # noqa: F401
    AdmissionController, ShedDecision)
from ray_tpu.serve._private.proxy_fleet.fleet import (  # noqa: F401
    ProxyFleetManager)
from ray_tpu.serve._private.proxy_fleet.proxy import (  # noqa: F401
    AsyncProxyActor)
