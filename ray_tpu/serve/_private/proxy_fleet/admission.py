"""Admission control for the ingress fleet: admit, queue, or shed.

reference parity: the reference proxy's backpressure
(`max_queued_requests`, proxy request-queue limits) + serve's
RESOURCE_EXHAUSTED shedding. Scaled to this runtime: each proxy runs
one AdmissionController on its event loop (single-threaded — no
locks), deciding per request:

  - **capacity**: a deployment admits up to
    `replicas x max_concurrent_queries` in-flight requests plus
    `max_queued_requests` queued beyond capacity (deployment override,
    else `Config.serve_max_queued_per_deployment`). Past that the
    request is shed — a bounded queue browns out; an unbounded one
    collapses (every admitted request times out).
  - **rate**: an optional per-deployment token bucket
    (`rate_limit_rps`, burst = 1s of tokens) sheds the overflow fast
    instead of queueing it into certain timeout.

Shed responses answer immediately: HTTP 503 with `Retry-After`, gRPC
RESOURCE_EXHAUSTED — and count into
`ray_tpu_serve_shed_total{deployment,reason}` (first-class RED, probed
by the `serve_shed_burn` watchdog).

Capacity follows the routing info the proxy's handles already hold
(replica count + max_concurrent_queries pushed by the controller's
long poll), so scaling a deployment up raises its admission ceiling
within one push.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, Optional


@dataclass
class ShedDecision:
    """Why a request was refused, and when to come back."""

    reason: str          # "capacity" | "rate_limit" | "draining"
    retry_after_s: float
    detail: str


class _TokenBucket:
    __slots__ = ("rate", "burst", "tokens", "stamp")

    def __init__(self, rate: float):
        self.rate = rate
        self.burst = max(1.0, rate)  # 1s worth of burst
        self.tokens = self.burst
        self.stamp = time.monotonic()

    def try_take(self) -> bool:
        now = time.monotonic()
        self.tokens = min(self.burst,
                          self.tokens + (now - self.stamp) * self.rate)
        self.stamp = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class AdmissionController:
    """Single-threaded (event-loop confined) admission state for one
    proxy. `try_admit` either claims an in-flight slot (caller MUST
    pair it with `release`) or returns a ShedDecision."""

    def __init__(self) -> None:
        self._inflight: Dict[str, int] = {}
        self._buckets: Dict[str, _TokenBucket] = {}
        # routing-derived ceilings, refreshed by the proxy whenever a
        # handle's routing info moves: deployment -> (capacity, queue)
        self._limits: Dict[str, tuple] = {}
        self.shed_total = 0

    # -- limits -------------------------------------------------------

    def update_limits(self, deployment: str, *, replicas: int,
                      max_concurrent_queries: int,
                      max_queued_requests: int,
                      rate_limit_rps: float) -> None:
        from ray_tpu._private.config import Config
        queued = (max_queued_requests if max_queued_requests >= 0
                  else Config.serve_max_queued_per_deployment)
        # an unknown/scaled-to-zero deployment still admits a probe's
        # worth of requests so routing errors surface as 404/500, not
        # a masking 503
        capacity = max(1, replicas) * max(1, max_concurrent_queries)
        self._limits[deployment] = (capacity, queued)
        rate = float(rate_limit_rps or 0.0)
        cur = self._buckets.get(deployment)
        if rate <= 0:
            self._buckets.pop(deployment, None)
        elif cur is None or cur.rate != rate:
            self._buckets[deployment] = _TokenBucket(rate)

    def limits(self, deployment: str) -> tuple:
        from ray_tpu._private.config import Config
        return self._limits.get(
            deployment, (16, Config.serve_max_queued_per_deployment))

    # -- admission ----------------------------------------------------

    def try_admit(self, deployment: str) -> Optional[ShedDecision]:
        """None = admitted (slot claimed); ShedDecision = refused.
        Capacity is checked BEFORE the token bucket: a capacity-shed
        request must not burn a token, or a burst against a full
        deployment drains the bucket while serving nothing and then
        rate-sheds the very requests capacity could take."""
        from ray_tpu._private.config import Config
        retry = Config.serve_shed_retry_after_s
        capacity, queued = self.limits(deployment)
        limit = capacity + queued
        cur = self._inflight.get(deployment, 0)
        if cur >= limit:
            self.shed_total += 1
            return ShedDecision(
                "capacity", retry,
                f"deployment {deployment!r} at admission limit "
                f"({cur} in flight >= {capacity} replica slots + "
                f"{queued} queued)")
        bucket = self._buckets.get(deployment)
        if bucket is not None and not bucket.try_take():
            self.shed_total += 1
            return ShedDecision(
                "rate_limit", retry,
                f"deployment {deployment!r} over its "
                f"{bucket.rate:g} req/s rate limit")
        self._inflight[deployment] = cur + 1
        return None

    def release(self, deployment: str) -> None:
        cur = self._inflight.get(deployment, 1) - 1
        if cur <= 0:
            self._inflight.pop(deployment, None)
        else:
            self._inflight[deployment] = cur

    def inflight(self, deployment: Optional[str] = None) -> int:
        if deployment is not None:
            return self._inflight.get(deployment, 0)
        return sum(self._inflight.values())

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        for dep in set(self._inflight) | set(self._limits):
            capacity, queued = self.limits(dep)
            bucket = self._buckets.get(dep)
            out[dep] = {
                "inflight": self._inflight.get(dep, 0),
                "capacity": capacity,
                "max_queued": queued,
                "rate_limit_rps": bucket.rate if bucket else 0.0,
            }
        return out
