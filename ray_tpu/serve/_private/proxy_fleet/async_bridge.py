"""ObjectRef -> asyncio bridge: await task results without per-request
threads.

The threading proxy parked one handler thread per request in
`ray_tpu.get` — its thread pool was the throughput ceiling (VERDICT
Weak §8). Here the core worker's completion callback
(`CoreWorker.add_done_callback`, PR-12) wakes the proxy's event loop
instead: the event loop never blocks on remote work, and a node's whole
ingress runs on ONE loop thread plus a small bounded submit pool for
the handle's (blocking) routing calls.

reference parity: serve/_private/proxy.py drives handles through
asyncio natively; this bridge is the equivalent seam for a sync core
worker API.
"""

from __future__ import annotations

import asyncio
from typing import Any, Optional


async def await_ref(ref: Any, loop: asyncio.AbstractEventLoop,
                    timeout: Optional[float] = None) -> None:
    """Block THIS COROUTINE (never the loop) until `ref` resolves.

    Raises asyncio.TimeoutError past `timeout`. Resolution includes
    error results — the subsequent materialize surfaces them."""
    from ray_tpu._private import worker as worker_mod
    fut: "asyncio.Future" = loop.create_future()

    def _done() -> None:  # fires on a completion-handling thread
        try:
            loop.call_soon_threadsafe(_resolve)
        except RuntimeError:  # loop already closed (proxy stopping)
            pass

    def _resolve() -> None:
        if not fut.done():
            fut.set_result(None)

    cw = worker_mod.global_worker().core_worker
    start = loop.time()
    cw.add_done_callback(ref, _done)
    await asyncio.wait_for(fut, timeout)
    # budget enforced on wake, not just by the timer: when a loaded
    # box stalls the loop past BOTH the timeout timer and the result's
    # call_soon_threadsafe, the resolve callback is queued first and
    # wait_for reports success for a request that blew its deadline —
    # the caller (e.g. the ingress 504 path) must still see a timeout
    if timeout is not None and loop.time() - start > timeout:
        raise asyncio.TimeoutError
