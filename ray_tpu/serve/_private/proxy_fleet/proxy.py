"""AsyncProxyActor: per-node asyncio ingress (HTTP + gRPC, one loop).

reference parity: serve/_private/proxy.py (HTTPProxy + gRPCProxy share
one event loop per node). Replaces the threading proxy as the default
ingress: request parsing/routing is async, handle submits bridge
through the core worker's done callbacks (async_bridge.py — no
per-request threads), large bytes results stream zero-copy
(http.py Response), admission control sheds overload fast
(admission.py), and a drain lifecycle (stop accepting → finish
in-flight → deregister) makes rolling updates and node removal
invisible to clients.

Request contract (unchanged from the threading proxy — see
serve/proxy.py history): POST/GET /<deployment> with a JSON body
(object → kwargs, anything else → one positional arg) returns
{"result": ...}; errors return {"error", "request_id"} with 404/400/
503/504/500; X-Request-Id is honored/minted/echoed; every request
records spans + RED metrics + the slow/error ring. New:

  - 503 + Retry-After when admission sheds (capacity / rate limit /
    draining), counted in ray_tpu_serve_shed_total{deployment,reason};
  - raw `bytes` results ship as application/octet-stream, streamed in
    bounded chunks straight from the store envelope view (PR-3);
  - requests to @serve.batch deployments with single-positional bodies
    coalesce proxy-side into one replica submit (serve_coalesce_*
    knobs) so the MXU sees fused batches even when every client sends
    one request at a time;
  - replicas replaced under a request (rolling update) retry through a
    forced routing refresh instead of surfacing 5xx.

gRPC rides the same loop and the same generic-service wire contract as
serve/grpc_proxy.py (`/ray_tpu.serve/<deployment>`, pickled
(args, kwargs) in, pickled result out, x-request-id metadata): shed →
RESOURCE_EXHAUSTED with a retry-after trailing-metadata hint.
"""

from __future__ import annotations

import asyncio
import json
import pickle
import threading
from time import perf_counter
from typing import Any, Dict, List, Optional

from ray_tpu.serve._private.proxy_fleet import http as fleet_http
from ray_tpu.serve._private.proxy_fleet.admission import (
    AdmissionController, ShedDecision)
from ray_tpu.serve._private.proxy_fleet.async_bridge import await_ref

GRPC_SERVICE_PREFIX = "/ray_tpu.serve/"


def _control_group(fn):
    fn.__ray_tpu_method_options__ = {"concurrency_group": "control"}
    return fn


def _retryable_replica_error(e: BaseException) -> bool:
    """Errors that mean THIS REPLICA is gone, not that the request is
    bad: retried through a forced routing refresh (rolling updates
    replace every replica; in-flight requests must not surface 5xx)."""
    import ray_tpu
    if isinstance(e, (ray_tpu.exceptions.RayActorError,
                      ray_tpu.exceptions.WorkerCrashedError,
                      ray_tpu.exceptions.OwnerDiedError)):
        return True
    # a task error WRAPPING an actor death (executor-side kill lands as
    # RayTaskError(cause=ActorDiedError) on some paths)
    cause = getattr(e, "cause", None)
    if cause is not None and isinstance(
            cause, (ray_tpu.exceptions.RayActorError,
                    ray_tpu.exceptions.WorkerCrashedError)):
        return True
    # transient empty replica set mid-redeploy
    return isinstance(e, RuntimeError) and "has no replicas" in str(e)


class _Coalescer:
    """Event-loop-confined fuser: single-positional requests for one
    @serve.batch deployment collect for up to serve_coalesce_wait_s (or
    serve_coalesce_max_batch) and ship as ONE handle_request_batch
    submit; the replica fans them into its batch queue, so one proxy
    batch becomes one fused forward pass."""

    def __init__(self, proxy: "AsyncProxy", deployment: str):
        self._proxy = proxy
        self._deployment = deployment
        self._pending: List[tuple] = []  # (arg, future)
        self._timer: Optional[asyncio.TimerHandle] = None

    def submit(self, arg: Any) -> "asyncio.Future":
        from ray_tpu._private.config import Config
        fut = self._proxy._loop.create_future()
        self._pending.append((arg, fut))
        if len(self._pending) >= Config.serve_coalesce_max_batch:
            self._flush()
        elif self._timer is None:
            self._timer = self._proxy._loop.call_later(
                Config.serve_coalesce_wait_s, self._flush)
        return fut

    def _flush(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        task = self._proxy._loop.create_task(self._run(batch))
        self._proxy._track_task(task)

    async def _run(self, batch: List[tuple]) -> None:
        try:
            results = await self._proxy._call_batch(
                self._deployment, [arg for arg, _f in batch])
            for (arg, fut), (ok, payload) in zip(batch, results):
                if fut.done():
                    continue
                if ok:
                    fut.set_result(payload)
                else:
                    fut.set_exception(RuntimeError(payload))
            # a short reply (replica bug) must fail its items, not
            # strand them until the request deadline
            for _arg, fut in batch[len(results):]:
                if not fut.done():
                    fut.set_exception(RuntimeError(
                        "batched replica reply missing this item"))
        except BaseException as e:  # noqa: BLE001 — fan the batch's
            for _arg, fut in batch:  # failure out to every waiter
                if not fut.done():
                    fut.set_exception(
                        e if isinstance(e, Exception)
                        else RuntimeError(repr(e)))


class AsyncProxy:
    """The in-process engine (event loop + servers + admission). Split
    from the actor shell so tests can drive it without a cluster
    round trip for every assertion."""

    SUBMIT_POOL_SIZE = 4
    RETRY_ATTEMPTS = 3

    def __init__(self, http_port: int = 8000,
                 grpc_port: Optional[int] = None,
                 request_timeout_s: Optional[float] = None,
                 host: str = "127.0.0.1"):
        import concurrent.futures

        from ray_tpu._private.config import Config
        from ray_tpu.serve import _telemetry

        self._timeout = float(request_timeout_s
                              if request_timeout_s is not None
                              else Config.serve_request_timeout_s)
        self._ring = _telemetry.RequestRing()
        self._handles: Dict[str, Any] = {}
        self._admission = AdmissionController()
        self._coalescers: Dict[str, _Coalescer] = {}
        self._tasks: set = set()
        self._draining = False
        self._drained = threading.Event()
        self._host = host
        # bounded pool for the handle's blocking routing calls (refresh
        # RPC, queue-len probes) and resolved-ref materializes — shared
        # by every request, NOT per-request
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self.SUBMIT_POOL_SIZE,
            thread_name_prefix="serve-proxy-submit")
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, daemon=True,
            name="serve-async-proxy")
        self._thread.start()
        self._http = fleet_http.HTTPServer(self._handle_http,
                                           host=host)
        self.http_port = asyncio.run_coroutine_threadsafe(
            self._http.start(http_port), self._loop).result(timeout=30)
        self.grpc_port: Optional[int] = None
        self._grpc_server = None
        if grpc_port is not None:
            self.grpc_port = asyncio.run_coroutine_threadsafe(
                self._start_grpc(grpc_port), self._loop).result(
                timeout=30)

    # ---- shared dispatch machinery ----------------------------------

    def _track_task(self, task: "asyncio.Task") -> None:
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    def _handle(self, name: str):
        """Handle cache lookup-or-create. MUST run on an executor
        thread: DeploymentHandle.__init__ resolves the controller (a
        blocking RPC the event loop can never make)."""
        handle = self._handles.get(name)
        if handle is None:
            from ray_tpu.serve.api import DeploymentHandle
            handle = DeploymentHandle(name)
            # benign create race between executor threads: last one
            # wins, both route correctly
            self._handles[name] = handle
        return handle

    def _refresh_admission(self, name: str, handle: Any) -> None:
        extra = getattr(handle, "_routing_extra", None) or {}
        self._admission.update_limits(
            name,
            replicas=extra.get("replica_count", 1),
            max_concurrent_queries=extra.get(
                "max_concurrent_queries", 16),
            max_queued_requests=extra.get("max_queued_requests", -1),
            rate_limit_rps=extra.get("rate_limit_rps", 0.0))

    async def _submit_and_get(self, name: str, submit_fn, trace_id: str,
                              deadline: float,
                              stages: Optional[Dict[str, float]] = None
                              ) -> Any:
        """The shared async request engine: run `submit_fn(handle)` on
        the bounded executor (the routing path blocks on controller
        RPCs and queue-len probes), await the returned ref via the
        done-callback bridge, then materialize — also on the executor
        (a large result's store fetch must not stall the loop).
        Replica-death errors (rolling update replaced the replica set,
        chaos killed a worker) force a routing refresh and retry inside
        the request's deadline instead of surfacing 5xx."""
        import ray_tpu
        from ray_tpu.util import tracing
        last: Optional[BaseException] = None
        for attempt in range(self.RETRY_ATTEMPTS + 1):
            remaining = deadline - perf_counter()
            if remaining <= 0:
                raise ray_tpu.exceptions.GetTimeoutError(
                    f"deployment {name!r} timed out")

            def _submit():
                handle = self._handle(name)
                with tracing.use_trace(trace_id):
                    if attempt > 0:
                        handle._refresh(force=True)
                    return handle, submit_fn(handle)

            try:
                t0 = perf_counter()
                handle, ref = await self._loop.run_in_executor(
                    self._pool, _submit)
                if stages is not None:
                    stages["route_s"] = perf_counter() - t0
                self._refresh_admission(name, handle)
                # budget re-read AFTER the (blocking) submit phase: a
                # slow routing fetch must shrink the await window, or a
                # result landing just past the deadline beats the stale
                # timer and a deserved 504 becomes a late 200
                await await_ref(ref, self._loop,
                                max(0.0, deadline - perf_counter()))
                return await self._loop.run_in_executor(
                    self._pool,
                    lambda: ray_tpu.get(ref, timeout=30))
            except asyncio.TimeoutError:
                raise ray_tpu.exceptions.GetTimeoutError(
                    f"deployment {name!r} timed out") from None
            except Exception as e:  # noqa: BLE001 — split retryable
                if not _retryable_replica_error(e) or \
                        attempt >= self.RETRY_ATTEMPTS:
                    raise
                last = e
                # replicas moved under us (rolling update / chaos
                # kill): give the controller a beat to publish the
                # replacement set, then retry through a forced refresh
                await asyncio.sleep(min(0.2 * (attempt + 1),
                                        max(0.0, deadline
                                            - perf_counter())))
        raise last  # pragma: no cover — loop always returns/raises

    async def _call_batch(self, name: str,
                          items: List[Any]) -> List[tuple]:
        """Coalesced path: ONE handle_request_batch submit for N
        single-positional requests; returns [(ok, payload), ...]."""
        return await self._submit_and_get(
            name, lambda handle: handle._submit_batch(items),
            trace_id="", deadline=perf_counter() + self._timeout)

    def _coalescible(self, name: str, args: tuple,
                     kwargs: Dict[str, Any]) -> bool:
        if kwargs or len(args) != 1:
            return False
        handle = self._handles.get(name)
        extra = getattr(handle, "_routing_extra", None) or {}
        return bool(extra.get("coalesce"))

    async def _dispatch(self, name: str, args: tuple,
                        kwargs: Dict[str, Any], trace_id: str,
                        stages: Optional[Dict[str, float]] = None
                        ) -> Any:
        import ray_tpu
        deadline = perf_counter() + self._timeout
        if self._coalescible(name, args, kwargs):
            co = self._coalescers.get(name)
            if co is None:
                co = self._coalescers[name] = _Coalescer(self, name)
            try:
                # own deadline: a batch reply that never resolves this
                # item's future (replica bug, lost result) must 504,
                # not park the request coroutine forever
                return await asyncio.wait_for(co.submit(args[0]),
                                              self._timeout)
            except asyncio.TimeoutError:
                raise ray_tpu.exceptions.GetTimeoutError(
                    f"deployment {name!r} timed out") from None
        return await self._submit_and_get(
            name,
            lambda handle: handle._submit(args, kwargs, model_id="",
                                          stream=False),
            trace_id, deadline, stages)

    def _record_span(self, name: str, t0: float, trace_id: str,
                     **attrs: Any) -> None:
        """Span record on the (single-threaded) event loop: the span
        TLS is set only for the synchronous record call, so concurrent
        request coroutines can't bleed trace ids into each other."""
        from ray_tpu._private import spans as spans_lib
        prev = spans_lib.get_current_trace()
        spans_lib.set_current_trace(trace_id)
        try:
            spans_lib.end(name, t0, **attrs)
        finally:
            spans_lib.set_current_trace(prev)

    def _shed_entry(self, deployment: str, method: str,
                    decision: ShedDecision, trace_id: str,
                    t_start: float) -> None:
        from ray_tpu.serve import _telemetry
        _telemetry.count_shed(deployment, decision.reason)
        _telemetry.record_ingress(
            self._ring, deployment=deployment or "?", method=method,
            code=503, trace_id=trace_id,
            total_s=perf_counter() - t_start,
            stages={"shed": 1.0}, error=f"shed: {decision.detail}")

    # ---- HTTP -------------------------------------------------------

    async def _handle_http(self, req: "fleet_http.Request"
                           ) -> "fleet_http.Response":
        import ray_tpu
        from ray_tpu.serve import _telemetry
        from ray_tpu.serve.api import DeploymentNotFound
        t_start = perf_counter()
        name = req.path.strip("/").split("/")[0].split("?")[0]
        trace_id = _telemetry.ingress_trace_id(
            req.headers.get("x-request-id"))
        if name == "-":  # /-/healthz: fleet liveness, no deployment
            body = json.dumps({
                "status": "draining" if self._draining else "ok",
                "inflight": self._http.inflight}).encode()
            return fleet_http.Response(
                503 if self._draining else 200, body)
        stages: Dict[str, float] = {}
        code, err = 200, None
        headers = {"X-Request-Id": trace_id}
        body_out: Any = b""
        # parse: JSON body -> call shape
        t0 = perf_counter()
        args: tuple = ()
        kwargs: Dict[str, Any] = {}
        parse_error = None
        if req.body:
            try:
                parsed = json.loads(req.body)
                if isinstance(parsed, dict):
                    kwargs = parsed
                else:
                    args = (parsed,)
            except json.JSONDecodeError as e:
                parse_error = f"invalid JSON body: {e}"
        stages["parse_s"] = perf_counter() - t0
        if parse_error is not None:
            code, err = 400, parse_error
        elif not name:
            code, err = 404, "no deployment in path"
        else:
            # draining connections already close after their in-flight
            # response (http.py) — requests that got this far finish
            decision = self._admission.try_admit(name)
            if decision is not None:
                self._shed_entry(name, "http", decision, trace_id,
                                 t_start)
                return self._shed_response(decision, trace_id)
            try:
                t0 = perf_counter()
                result = await self._dispatch(name, args, kwargs,
                                              trace_id, stages)
                stages["handle_s"] = perf_counter() - t0 \
                    - stages.get("route_s", 0.0)
                t0 = perf_counter()
                if isinstance(result, (bytes, bytearray, memoryview)):
                    # zero-copy streaming: the store-envelope view
                    # flows straight to the socket in bounded chunks
                    body_out = result
                    headers["Content-Type"] = "application/octet-stream"
                else:
                    body_out = json.dumps({"result": result}).encode()
                stages["serialize_s"] = perf_counter() - t0
            except DeploymentNotFound as e:
                code, err = 404, str(e)
                # a path scan must not grow the handle cache forever
                self._handles.pop(name, None)
                self._coalescers.pop(name, None)
            except ray_tpu.exceptions.GetTimeoutError:
                code, err = 504, (
                    f"deployment {name!r} did not respond within "
                    f"{perf_counter() - t_start:.1f}s (request "
                    f"timeout {self._timeout:g}s)")
            except Exception as e:  # noqa: BLE001
                code, err = 500, str(e)
            finally:
                self._admission.release(name)
        if err is not None:
            body_out = json.dumps({"error": err,
                                   "request_id": trace_id}).encode()
        self._record_span("serve.proxy.request",
                          t_start, trace_id,
                          deployment=name, code=code)
        resp = fleet_http.Response(code, body_out, headers=headers)
        ring = self._ring

        def _on_written(nbytes: int, write_s: float,
                        write_err: Optional[str]) -> None:
            # record AFTER the write so the ring entry is complete
            stages["write_s"] = write_s
            final_code, final_err = code, err
            if write_err is not None:
                final_code = 499
                final_err = f"response write failed: {write_err}"
            self._record_span("serve.proxy.write",
                              perf_counter() - write_s, trace_id,
                              deployment=name, bytes=nbytes)
            _telemetry.record_ingress(
                ring, deployment=name or "?", method="http",
                code=final_code, trace_id=trace_id,
                total_s=perf_counter() - t_start,
                stages=stages, error=final_err)

        resp.on_written = _on_written
        return resp

    def _shed_response(self, decision: ShedDecision,
                       trace_id: str) -> "fleet_http.Response":
        body = json.dumps({
            "error": f"shed ({decision.reason}): {decision.detail}",
            "request_id": trace_id,
            "retry_after_s": decision.retry_after_s}).encode()
        return fleet_http.Response(
            503, body,
            headers={"X-Request-Id": trace_id,
                     "Retry-After": f"{decision.retry_after_s:g}"})

    # ---- gRPC -------------------------------------------------------

    async def _start_grpc(self, port: int) -> int:
        import grpc
        import grpc.aio
        proxy = self

        class _Generic(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                method = handler_call_details.method
                if not method.startswith(GRPC_SERVICE_PREFIX):
                    return None
                name = method[len(GRPC_SERVICE_PREFIX):]

                async def unary(request: bytes, context):
                    return await proxy._handle_grpc(name, request,
                                                    context)

                return grpc.unary_unary_rpc_method_handler(
                    unary, request_deserializer=None,
                    response_serializer=None)

        server = grpc.aio.server(
            options=[("grpc.max_receive_message_length", -1),
                     ("grpc.max_send_message_length", -1)])
        server.add_generic_rpc_handlers((_Generic(),))
        bound = server.add_insecure_port(f"{self._host}:{port}")
        if bound == 0:
            raise OSError(f"gRPC proxy could not bind "
                          f"{self._host}:{port}")
        await server.start()
        self._grpc_server = server
        return bound

    async def _handle_grpc(self, name: str, request: bytes,
                           context) -> bytes:
        import grpc

        import ray_tpu
        from ray_tpu.serve import _telemetry
        from ray_tpu.serve.api import DeploymentNotFound
        t_start = perf_counter()
        meta = dict(context.invocation_metadata() or ())
        trace_id = _telemetry.ingress_trace_id(meta.get("x-request-id"))
        context.set_trailing_metadata((("x-request-id", trace_id),))
        stages: Dict[str, float] = {}
        code, err, status = 200, None, None
        out = b""
        decision = self._admission.try_admit(name)
        if decision is not None:
            self._shed_entry(name, "grpc", decision, trace_id, t_start)
            context.set_trailing_metadata(
                (("x-request-id", trace_id),
                 ("retry-after", f"{decision.retry_after_s:g}")))
            await context.abort(
                grpc.StatusCode.RESOURCE_EXHAUSTED,
                f"shed ({decision.reason}): {decision.detail}")
        try:
            t0 = perf_counter()
            try:
                args, kwargs = pickle.loads(request) if request \
                    else ((), {})
            except Exception as e:
                raise ValueError(f"bad request payload: {e}") from e
            stages["parse_s"] = perf_counter() - t0
            t0 = perf_counter()
            result = await self._dispatch(name, tuple(args),
                                          dict(kwargs), trace_id,
                                          stages)
            stages["handle_s"] = perf_counter() - t0 \
                - stages.get("route_s", 0.0)
            t0 = perf_counter()
            out = pickle.dumps(result, protocol=5)
            stages["serialize_s"] = perf_counter() - t0
        except DeploymentNotFound as e:
            code, err = 404, str(e)
            status = grpc.StatusCode.NOT_FOUND
            self._handles.pop(name, None)
        except ray_tpu.exceptions.GetTimeoutError:
            code = 504
            err = (f"deployment {name!r} did not respond within "
                   f"{perf_counter() - t_start:.1f}s (request timeout "
                   f"{self._timeout:g}s)")
            status = grpc.StatusCode.DEADLINE_EXCEEDED
        except Exception as e:  # noqa: BLE001
            code, err = 500, str(e)
            status = grpc.StatusCode.INTERNAL
        finally:
            self._admission.release(name)
        self._record_span("serve.proxy.request", t_start, trace_id,
                          deployment=name, code=code, transport="grpc")
        _telemetry.record_ingress(
            self._ring, deployment=name, method="grpc", code=code,
            trace_id=trace_id, total_s=perf_counter() - t_start,
            stages=stages, error=err)
        if err is not None:
            await context.abort(status, err)
        return out

    # ---- lifecycle --------------------------------------------------

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Stop accepting, finish in-flight, then report drained.
        Blocking (called from an actor method thread, never the
        loop)."""
        from ray_tpu._private.config import Config
        budget = float(timeout_s if timeout_s is not None
                       else Config.serve_drain_timeout_s)
        self._draining = True
        ok = asyncio.run_coroutine_threadsafe(
            self._http.drain(budget), self._loop).result(
            timeout=budget + 10)
        if self._grpc_server is not None:
            async def _stop_grpc():
                await self._grpc_server.stop(grace=budget)
            try:
                asyncio.run_coroutine_threadsafe(
                    _stop_grpc(), self._loop).result(
                    timeout=budget + 10)
            except Exception:  # noqa: BLE001 - already stopping
                pass
        self._drained.set()
        return ok

    @property
    def draining(self) -> bool:
        return self._draining

    def drained(self) -> bool:
        return self._drained.is_set()

    def inflight(self) -> int:
        return self._http.inflight + self._admission.inflight()

    def stop(self) -> None:
        if not self._drained.is_set():
            try:
                self.drain(timeout_s=2.0)
            except Exception:  # noqa: BLE001 - force-stop below anyway
                pass

        async def _shutdown():
            await self._http.stop()
            for t in list(self._tasks):
                t.cancel()

        try:
            asyncio.run_coroutine_threadsafe(
                _shutdown(), self._loop).result(timeout=10)
        except Exception:  # noqa: BLE001 - loop wedged; stop it anyway
            pass
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)
        self._pool.shutdown(wait=False)

    def status(self) -> Dict[str, Any]:
        return {
            "http_port": self.http_port,
            "grpc_port": self.grpc_port,
            "draining": self._draining,
            "drained": self._drained.is_set(),
            "inflight": self._http.inflight,
            "admission": self._admission.snapshot(),
            "shed_total": self._admission.shed_total,
        }


class AsyncProxyActor:
    """Actor shell over AsyncProxy (the fleet manager starts one per
    node; serve.start_http starts one on the local node). Control-group
    methods stay responsive while a drain blocks the default group."""

    def __init__(self, http_port: int = 8000,
                 grpc_port: Optional[int] = None,
                 request_timeout_s: Optional[float] = None,
                 node_id: str = ""):
        self._proxy = AsyncProxy(http_port=http_port,
                                 grpc_port=grpc_port,
                                 request_timeout_s=request_timeout_s)
        self.node_id = node_id
        # the RAW constructor args (0 = ephemeral port, None = config
        # default), not the resolved values: the fleet's adopt path
        # compares these against its armed config — a predecessor from
        # an older fleet generation must not serve a newer config
        self._armed = {"http_port": http_port, "grpc_port": grpc_port,
                       "request_timeout_s": request_timeout_s}

    @_control_group
    def armed_config(self) -> Dict[str, Any]:
        return dict(self._armed)

    @_control_group
    def ready(self) -> int:
        return self._proxy.http_port

    @_control_group
    def ports(self) -> Dict[str, Optional[int]]:
        return {"http": self._proxy.http_port,
                "grpc": self._proxy.grpc_port}

    @_control_group
    def ping(self) -> str:
        return "drained" if self._proxy.drained() else \
            ("draining" if self._proxy.draining else "pong")

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        return self._proxy.drain(timeout_s)

    @_control_group
    def drained(self) -> bool:
        return self._proxy.drained()

    @_control_group
    def status(self) -> Dict[str, Any]:
        out = self._proxy.status()
        out["node_id"] = self.node_id
        return out

    @_control_group
    def requests_snapshot(self, deployment: Optional[str] = None,
                          errors: bool = False,
                          slowest: Optional[int] = None):
        """Captured slow/errored requests (see _telemetry.RequestRing)
        — queried by util.state.serve_requests() across all proxies."""
        return self._proxy._ring.snapshot(
            deployment=deployment, errors=errors, slowest=slowest)

    def stop(self) -> None:
        self._proxy.stop()
