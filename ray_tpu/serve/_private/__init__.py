"""Serve-internal subsystems (reference python/ray/serve/_private)."""
