"""@serve.batch: transparent request batching inside replicas.

reference parity: python/ray/serve/batching.py — a decorated method
receives a LIST of requests and returns a LIST of results; concurrent
callers are coalesced up to max_batch_size, waiting at most
batch_wait_timeout_s for stragglers. TPU-first motivation: the MXU wants
batched inference, so the router's individual requests must fuse into
one forward pass at the replica. Thread-based here (replica actors run
handle_request on max_concurrent_queries exec threads).
"""

from __future__ import annotations

import functools
import queue
import threading
from concurrent.futures import Future
from typing import Any, Callable, List, Tuple


# per-process; replicas resolve it by module import, so it never pickles
_INIT_LOCK = threading.Lock()


class _BatchQueue:
    def __init__(self, fn: Callable, owner: Any, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self._fn = fn
        self._owner = owner
        self._max = max_batch_size
        self._timeout = batch_wait_timeout_s
        self._q: "queue.Queue[Tuple[Any, Future]]" = queue.Queue()
        self._thread = threading.Thread(
            target=self._worker, daemon=True,
            name=f"serve-batch-{getattr(fn, '__name__', 'fn')}")
        self._thread.start()

    def submit(self, item: Any) -> Future:
        fut: Future = Future()
        self._q.put((item, fut))
        return fut

    def _collect(self) -> List[Tuple[Any, Future]]:
        first = self._q.get()
        batch = [first]
        import time
        deadline = time.monotonic() + self._timeout
        while len(batch) < self._max:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                # deadline passed: take only what's already queued
                try:
                    batch.append(self._q.get_nowait())
                    continue
                except queue.Empty:
                    break
            try:
                batch.append(self._q.get(timeout=remaining))
            except queue.Empty:
                break
        return batch

    def _worker(self) -> None:
        while True:
            batch = self._collect()
            items = [b[0] for b in batch]
            try:
                results = self._fn(self._owner, items)
                if len(results) != len(items):
                    raise ValueError(
                        f"batched function returned {len(results)} "
                        f"results for {len(items)} requests")
                for (_, fut), r in zip(batch, results):
                    fut.set_result(r)
            except Exception as e:  # noqa: BLE001
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)


def batch(_func: Callable = None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorate a replica method taking (self, items: List) -> List.
    Callers invoke it with a SINGLE item; concurrent calls coalesce.
    """

    def deco(fn: Callable) -> Callable:
        attr = f"__serve_batch_queue_{fn.__name__}"

        def _ensure_queue(self):
            q = getattr(self, attr, None)
            if q is None:
                # the module-level lock guards first-call queue init.
                # Resolved via import AT CALL TIME: the wrapper pickles
                # by value into replicas, and a lock captured in the
                # closure or as a global would (a) race its own
                # creation or (b) fail to pickle.
                import ray_tpu.serve.batching as _mod
                with _mod._INIT_LOCK:
                    q = getattr(self, attr, None)
                    if q is None:
                        q = _mod._BatchQueue(fn, self, max_batch_size,
                                             batch_wait_timeout_s)
                        setattr(self, attr, q)
            return q

        @functools.wraps(fn)
        def wrapper(self, item: Any):
            return _ensure_queue(self).submit(item).result()

        def _submit_many(self, items: List[Any]) -> List[Future]:
            """Enqueue a proxy-coalesced batch WITHOUT blocking between
            items (every item must be in the queue before anyone waits,
            or the fused forward pass degenerates to per-item passes).
            Used by Replica.handle_request_batch; returns the futures
            in item order."""
            q = _ensure_queue(self)
            return [q.submit(i) for i in items]

        wrapper._serve_batch = True  # type: ignore[attr-defined]
        wrapper._serve_batch_submit_many = _submit_many  # type: ignore[attr-defined]
        return wrapper

    if _func is not None:
        return deco(_func)
    return deco
