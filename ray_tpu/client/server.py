"""Client proxy server: hosts driver state for thin clients.

reference parity: python/ray/util/client/server/ (proxier + per-client
server translating the client protocol into core-API calls). The proxy
process is itself a cluster driver; every connected client's refs live
here, tracked per client id so a disconnect releases them.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)


class ClientProxyServer:
    def __init__(self, gcs_address: str, host: str = "127.0.0.1",
                 port: int = 0):
        import ray_tpu
        from ray_tpu._private import rpc as rpc_lib

        ray_tpu.init(gcs_address, ignore_reinit_error=True)
        self._rt = ray_tpu
        self._lock = threading.Lock()
        # client id -> {ref hex -> ObjectRef} (holds the proxy-side pin)
        self._client_refs: Dict[str, Dict[str, Any]] = {}
        # client id -> {fn key -> RemoteFunction}
        self._client_fns: Dict[str, Dict[str, Any]] = {}
        # client id -> {actor id hex -> (ActorHandle, created_by_client)}
        # created_by_client distinguishes actors the client made (killed
        # on disconnect) from named actors it merely looked up
        self._client_actors: Dict[str, Dict[str, Any]] = {}

        self.server = rpc_lib.RpcServer({
            "cl_register_fn": self.register_fn,
            "cl_task": self.submit_task,
            "cl_put": self.put,
            "cl_get": self.get,
            "cl_wait": self.wait,
            "cl_create_actor": self.create_actor,
            "cl_actor_call": self.actor_call,
            "cl_kill_actor": self.kill_actor,
            "cl_get_named_actor": self.get_named_actor,
            "cl_release": self.release,
            "cl_disconnect": self.disconnect,
            "cl_cluster_info": self.cluster_info,
            "cl_ping": lambda: "pong",
        }, host=host, port=port)
        self.address = self.server.address

    # -- helpers -----------------------------------------------------

    def _track(self, client_id: str, refs: List[Any]) -> List[bytes]:
        out = []
        with self._lock:
            table = self._client_refs.setdefault(client_id, {})
            for r in refs:
                table[r.hex()] = r
                out.append(r.id.binary())
        return out

    def _lookup(self, client_id: str, ref_bins: List[bytes]) -> List[Any]:
        with self._lock:
            table = self._client_refs.get(client_id, {})
            return [table[b.hex()] for b in ref_bins]

    # -- handlers ----------------------------------------------------

    def register_fn(self, client_id: str, fn_blob: bytes,
                    options: Dict[str, Any]) -> str:
        import cloudpickle
        fn = cloudpickle.loads(fn_blob)
        rf = self._rt.remote(fn)
        if options:
            rf = rf.options(**options)
        key = f"{client_id}:{getattr(fn, '__name__', 'fn')}:{id(rf)}"
        with self._lock:
            self._client_fns.setdefault(client_id, {})[key] = rf
        return key

    def _materialize_args(self, client_id: str, args_blob: bytes):
        """Client refs at ANY pickle depth resolve to the proxy's real
        ObjectRefs: ClientObjectRef.__reduce__ routes through
        _resolve_ref, which consults the resolver installed here for the
        duration of the unpickle."""
        import pickle

        from ray_tpu.client.worker import _proxy_resolver
        _proxy_resolver.resolver = \
            lambda b: self._lookup(client_id, [b])[0]
        try:
            args, kwargs = pickle.loads(args_blob)
        finally:
            _proxy_resolver.resolver = None
        return args, kwargs

    def submit_task(self, client_id: str, fn_key: str, args_blob: bytes,
                    options: Dict[str, Any]) -> List[bytes]:
        with self._lock:
            rf = self._client_fns[client_id][fn_key]
        if options:
            rf = rf.options(**options)
        args, kwargs = self._materialize_args(client_id, args_blob)
        refs = rf.remote(*args, **kwargs)
        if not isinstance(refs, list):
            refs = [refs]
        return self._track(client_id, refs)

    def put(self, client_id: str, value_blob: bytes) -> List[bytes]:
        import pickle
        ref = self._rt.put(pickle.loads(value_blob))
        return self._track(client_id, [ref])

    def get(self, client_id: str, ref_bins: List[bytes],
            timeout: Optional[float]) -> bytes:
        refs = self._lookup(client_id, ref_bins)
        values = self._rt.get(refs, timeout=timeout)
        return self._dumps_translating_refs(client_id, values)

    def _dumps_translating_refs(self, client_id: str, value: Any) -> bytes:
        """Pickle result values so any contained ObjectRef (e.g. a
        num_returns="dynamic" handle's list of refs, or refs returned by
        tasks) crosses to the client as a ClientObjectRef, tracked
        proxy-side like every other client ref."""
        import io

        from cloudpickle import CloudPickler

        from ray_tpu._private.object_ref import ObjectRef
        from ray_tpu.client.worker import _resolve_ref
        server = self

        # CloudPickler (not plain Pickler): values may be instances of
        # classes the client shipped by value from its __main__
        class _Pickler(CloudPickler):
            def reducer_override(inner, obj):  # noqa: N805
                if isinstance(obj, ObjectRef):
                    server._track(client_id, [obj])
                    return (_resolve_ref, (obj.id.binary(),))
                return super().reducer_override(obj)

        buf = io.BytesIO()
        _Pickler(buf, protocol=5).dump(value)
        return buf.getvalue()

    def wait(self, client_id: str, ref_bins: List[bytes],
             num_returns: int, timeout: Optional[float]):
        refs = self._lookup(client_id, ref_bins)
        ready, rest = self._rt.wait(refs, num_returns=num_returns,
                                    timeout=timeout)
        return ([r.id.binary() for r in ready],
                [r.id.binary() for r in rest])

    def create_actor(self, client_id: str, cls_blob: bytes, args_blob: bytes,
                     options: Dict[str, Any]) -> bytes:
        import cloudpickle
        cls = cloudpickle.loads(cls_blob)
        ac = self._rt.remote(cls)
        if options:
            ac = ac.options(**options)
        args, kwargs = self._materialize_args(client_id, args_blob)
        handle = ac.remote(*args, **kwargs)
        # get_if_exists may have returned a PRE-EXISTING shared actor:
        # treat those as not-ours so disconnect can't kill an actor other
        # clients rely on (conservative: a genuinely fresh get_if_exists
        # actor then outlives the client, which matches its
        # shared-by-name intent)
        created = not options.get("get_if_exists", False)
        with self._lock:
            table = self._client_actors.setdefault(client_id, {})
            prev = table.get(handle._actor_id.hex())
            table[handle._actor_id.hex()] = (
                handle, created if prev is None else prev[1])
        return handle._actor_id.binary()

    def actor_call(self, client_id: str, actor_id_bin: bytes,
                   method_name: str, args_blob: bytes) -> List[bytes]:
        with self._lock:
            handle, _ = self._client_actors[client_id][actor_id_bin.hex()]
        args, kwargs = self._materialize_args(client_id, args_blob)
        out = getattr(handle, method_name).remote(*args, **kwargs)
        # @method(num_returns=N) tags make .remote() return a LIST of
        # refs; flatten so tracking and the client see each ref
        refs = out if isinstance(out, list) else [out]
        return self._track(client_id, refs)

    def get_named_actor(self, client_id: str, name: str,
                        namespace: str = "") -> bytes:
        handle = self._rt.get_actor(name, namespace=namespace)
        with self._lock:
            table = self._client_actors.setdefault(client_id, {})
            prev = table.get(handle._actor_id.hex())
            # looking up an actor this client CREATED must not demote it
            # to not-ours (it would leak past disconnect)
            table[handle._actor_id.hex()] = (
                handle, False if prev is None else prev[1])
        return handle._actor_id.binary()

    def kill_actor(self, client_id: str, actor_id_bin: bytes,
                   no_restart: bool = True) -> None:
        with self._lock:
            entry = self._client_actors.get(client_id, {}).pop(
                actor_id_bin.hex(), None)
        if entry is not None:
            self._rt.kill(entry[0], no_restart=no_restart)

    def release(self, client_id: str, ref_bins: List[bytes]) -> None:
        with self._lock:
            table = self._client_refs.get(client_id, {})
            for b in ref_bins:
                table.pop(b.hex(), None)

    def disconnect(self, client_id: str) -> None:
        with self._lock:
            self._client_refs.pop(client_id, None)
            self._client_fns.pop(client_id, None)
            actors = self._client_actors.pop(client_id, {})
        for handle, created in actors.values():
            if not created:
                continue  # merely looked-up named actors aren't ours
            try:
                self._rt.kill(handle)
            except Exception:  # noqa: BLE001 - client's actor already dead
                pass

    def cluster_info(self) -> Dict[str, Any]:
        return {"nodes": len(self._rt.nodes()),
                "resources": self._rt.cluster_resources()}

    def stop(self) -> None:
        self.server.stop()


def serve_forever(gcs_address: str, host: str = "127.0.0.1",
                  port: int = 10001) -> None:
    import time
    proxy = ClientProxyServer(gcs_address, host=host, port=port)
    # operator handshake on stdout: scripts scrape the ray:// address
    print(f"client proxy listening on "  # graftlint: disable=RT012
          f"ray://{proxy.address[0]}:{proxy.address[1]}", flush=True)
    try:
        while True:
            time.sleep(1)
    finally:
        proxy.stop()
