"""ray_tpu.client: thin-client proxy mode (`ray_tpu.init("ray://...")`).

reference parity: python/ray/util/client — a remote driver connects to a
proxy server inside the cluster over ONE connection; the proxy hosts the
actual core-worker state and translates client calls into the core API
(client worker.py / server/proxier.py). Use it when the driver machine
can reach only the proxy, not every node's RPC endpoints.
"""

from ray_tpu.client.server import ClientProxyServer, serve_forever  # noqa: F401
from ray_tpu.client.worker import (ClientActorHandle,  # noqa: F401
                                   ClientContext, ClientObjectRef,
                                   connect)

__all__ = ["ClientProxyServer", "serve_forever", "connect",
           "ClientContext", "ClientObjectRef", "ClientActorHandle"]
