"""Thin-client side of the proxy protocol.

reference parity: python/ray/util/client/worker.py — a ClientContext
installed by ray_tpu.init("ray://host:port"); remote functions/actors
created while connected proxy through it instead of a local core worker.
"""

from __future__ import annotations

import pickle
import queue
import threading
import uuid
from typing import Any, Dict, List, Optional

import cloudpickle

# Proxy-side resolver installed by the server thread unpickling client
# args: refs at ANY pickle depth resolve straight to the proxy's real
# ObjectRefs. On the client side (no resolver) they reconstruct as
# ClientObjectRefs against the process's active context.
_proxy_resolver = threading.local()
_active_context: Optional["ClientContext"] = None


def _resolve_ref(ref_bin: bytes):
    resolver = getattr(_proxy_resolver, "resolver", None)
    if resolver is not None:
        return resolver(ref_bin)
    if _active_context is None:
        raise RuntimeError("no active ray_tpu client context")
    return ClientObjectRef(ref_bin, _active_context)


class ClientObjectRef:
    __slots__ = ("_bin", "_ctx")

    def __init__(self, ref_bin: bytes, ctx: "ClientContext"):
        self._bin = ref_bin
        self._ctx = ctx

    def hex(self) -> str:
        return self._bin.hex()

    def __reduce__(self):
        # at any nesting depth in pickled args, resolve proxy-side
        return (_resolve_ref, (self._bin,))

    def __repr__(self) -> str:
        return f"ClientObjectRef({self.hex()[:16]})"

    def __del__(self):
        # async: a synchronous RPC here could deadlock if GC fires on a
        # thread already inside the (non-reentrant) RpcClient lock
        try:
            self._ctx._release_async(self._bin)
        except Exception:  # noqa: BLE001 - interpreter shutdown
            pass


class ClientRemoteFunction:
    def __init__(self, ctx: "ClientContext", fn: Any,
                 options: Optional[Dict[str, Any]] = None):
        self._ctx = ctx
        self._fn = fn
        self._options = dict(options or {})
        self._key: Optional[str] = None

    def options(self, **kwargs: Any) -> "ClientRemoteFunction":
        rf = ClientRemoteFunction(self._ctx, self._fn,
                                  {**self._options, **kwargs})
        rf._key = self._key
        return rf

    def remote(self, *args: Any, **kwargs: Any):
        ctx = self._ctx
        if self._key is None:
            self._key = ctx._call(
                "cl_register_fn", fn_blob=cloudpickle.dumps(self._fn),
                options={})
        ref_bins = ctx._call(
            "cl_task", fn_key=self._key,
            args_blob=cloudpickle.dumps((args, kwargs)),
            options=self._options)
        refs = [ClientObjectRef(b, ctx) for b in ref_bins]
        num_returns = self._options.get("num_returns", 1)
        if num_returns in ("dynamic", "streaming"):
            num_returns = 1  # the handle is the single return
        return refs if (num_returns != 1 or len(refs) > 1) else refs[0]


class _ClientActorMethod:
    def __init__(self, handle: "ClientActorHandle", name: str):
        self._handle = handle
        self._name = name

    def remote(self, *args: Any, **kwargs: Any):
        ctx = self._handle._ctx
        ref_bins = ctx._call(
            "cl_actor_call", actor_id_bin=self._handle._actor_id_bin,
            method_name=self._name,
            args_blob=cloudpickle.dumps((args, kwargs)))
        if len(ref_bins) == 1:
            return ClientObjectRef(ref_bins[0], ctx)
        # @method(num_returns=N): one client ref per return value
        return [ClientObjectRef(b, ctx) for b in ref_bins]


class ClientActorHandle:
    def __init__(self, ctx: "ClientContext", actor_id_bin: bytes):
        self._ctx = ctx
        self._actor_id_bin = actor_id_bin

    def __getattr__(self, name: str) -> _ClientActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClientActorMethod(self, name)


class ClientActorClass:
    def __init__(self, ctx: "ClientContext", cls: type,
                 options: Optional[Dict[str, Any]] = None):
        self._ctx = ctx
        self._cls = cls
        self._options = dict(options or {})

    def options(self, **kwargs: Any) -> "ClientActorClass":
        return ClientActorClass(self._ctx, self._cls,
                                {**self._options, **kwargs})

    def remote(self, *args: Any, **kwargs: Any) -> ClientActorHandle:
        ctx = self._ctx
        actor_bin = ctx._call(
            "cl_create_actor", cls_blob=cloudpickle.dumps(self._cls),
            args_blob=cloudpickle.dumps((args, kwargs)),
            options=self._options)
        return ClientActorHandle(ctx, actor_bin)


class ClientContext:
    """The per-process client session (reference client worker.py)."""

    def __init__(self, address: str):
        from ray_tpu._private import rpc as rpc_lib
        host, port = address.rsplit(":", 1)
        self.client_id = uuid.uuid4().hex[:12]
        # no socket timeout: a blocking get on a long task keeps this
        # connection legitimately silent for its whole runtime
        self._rpc = rpc_lib.RpcClient((host, int(port)), timeout=None)
        self._lock = threading.Lock()
        self._release_queue: "queue.Queue" = queue.Queue()
        threading.Thread(target=self._release_loop, daemon=True,
                         name="client-release").start()
        assert self._rpc.call("cl_ping") == "pong"
        global _active_context
        _active_context = self

    def _call(self, method: str, **kwargs: Any) -> Any:
        return self._rpc.call(method, client_id=self.client_id, **kwargs)

    def _release_async(self, ref_bin: bytes) -> None:
        self._release_queue.put(ref_bin)

    def _release_loop(self) -> None:
        while True:
            ref_bin = self._release_queue.get()
            if ref_bin is None:
                return
            # batch whatever else is queued
            bins = [ref_bin]
            try:
                while True:
                    nxt = self._release_queue.get_nowait()
                    if nxt is None:
                        return
                    bins.append(nxt)
            except queue.Empty:
                pass
            try:
                self._call("cl_release", ref_bins=bins)
            except Exception:  # noqa: BLE001 - proxy gone
                return

    # -- public surface mirrored by the api shims ---------------------

    def remote(self, target: Any, **options: Any):
        import inspect
        if inspect.isclass(target):
            return ClientActorClass(self, target, options)
        return ClientRemoteFunction(self, target, options)

    def put(self, value: Any) -> ClientObjectRef:
        ref_bins = self._call("cl_put", value_blob=cloudpickle.dumps(value))
        return ClientObjectRef(ref_bins[0], self)

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ClientObjectRef)
        if single:
            refs = [refs]
        blob = self._call("cl_get", ref_bins=[r._bin for r in refs],
                          timeout=timeout)
        values = pickle.loads(blob)
        return values[0] if single else values

    def wait(self, refs, num_returns: int = 1,
             timeout: Optional[float] = None):
        by_bin = {r._bin: r for r in refs}
        ready_bins, rest_bins = self._call(
            "cl_wait", ref_bins=[r._bin for r in refs],
            num_returns=num_returns, timeout=timeout)
        return ([by_bin[b] for b in ready_bins],
                [by_bin[b] for b in rest_bins])

    def get_actor(self, name: str, namespace: str = ""
                  ) -> ClientActorHandle:
        actor_bin = self._call(
            "cl_get_named_actor", name=name,
            namespace=namespace or getattr(self, "namespace", ""))
        return ClientActorHandle(self, actor_bin)

    def kill(self, actor: ClientActorHandle,
             no_restart: bool = True) -> None:
        self._call("cl_kill_actor", actor_id_bin=actor._actor_id_bin,
                   no_restart=no_restart)

    def cluster_info(self) -> Dict[str, Any]:
        return self._rpc.call("cl_cluster_info")

    def disconnect(self) -> None:
        self._release_queue.put(None)
        try:
            self._call("cl_disconnect")
        except Exception:  # noqa: BLE001 - server gone; disconnect is best-effort
            pass
        self._rpc.close()
        global _active_context
        if _active_context is self:
            _active_context = None


def connect(address: str) -> ClientContext:
    """address: 'host:port' of a running ClientProxyServer."""
    return ClientContext(address)
