"""ray_tpu: a TPU-native distributed compute framework.

A ground-up rebuild of the capabilities of Ray (reference at
/root/reference) designed TPU-first: tasks/actors/objects over a
shared-memory object store and lease-based scheduling; gang scheduling via
placement groups; a JaxTrainer whose train steps are pjit/shard_map XLA
programs over ICI meshes; and an RL stack (PPO/IMPALA) whose learners are
JIT'd JAX programs while CPU EnvRunner actors stream trajectories through
the object store.

Importing ray_tpu is deliberately jax-free and fast; ML subpackages
(ray_tpu.train, ray_tpu.rllib, ray_tpu.parallel, ray_tpu.models) import jax
lazily on first use.
"""

from ray_tpu import chaos  # noqa: F401
from ray_tpu import exceptions  # noqa: F401
from ray_tpu._private.object_ref import ObjectRef  # noqa: F401
from ray_tpu.actor import (ActorClass, ActorHandle, get_actor,  # noqa: F401
                           method)
from ray_tpu.api import (available_resources, cancel, cluster_resources,  # noqa: F401
                         free, get, get_gcs_address, get_runtime_context,
                         init, is_initialized, kill, nodes, put, remote,
                         shutdown, timeline, wait)
from ray_tpu.remote_function import RemoteFunction  # noqa: F401

__version__ = "0.1.0"

__all__ = [
    "ObjectRef", "ActorClass", "ActorHandle", "get_actor", "method",
    "remote", "init",
    "shutdown", "is_initialized", "get", "put", "wait", "kill", "cancel",
    "free", "nodes", "cluster_resources", "available_resources",
    "get_gcs_address", "get_runtime_context", "exceptions", "chaos",
    "RemoteFunction", "timeline", "__version__",
]
