"""Job supervisor actor + submission client.

reference parity: dashboard/modules/job/job_manager.py (JobSupervisor
runs the entrypoint as a subprocess, streams status) and sdk.py
(JobSubmissionClient.submit_job/get_job_status/get_job_logs).
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

_KV_PREFIX = "job::"

PENDING, RUNNING, SUCCEEDED, FAILED = \
    "PENDING", "RUNNING", "SUCCEEDED", "FAILED"
TERMINAL = (SUCCEEDED, FAILED)


class JobSupervisor:
    """Runs one job's entrypoint as a subprocess on its node; writes
    status + logs into the GCS KV (reference job_manager.py
    JobSupervisor.run)."""

    def __init__(self, job_id: str, entrypoint: str,
                 working_dir: Optional[str], gcs_address: str,
                 env_vars: Optional[Dict[str, str]] = None):
        self.job_id = job_id
        self.entrypoint = entrypoint
        self.working_dir = working_dir
        self.gcs_address = gcs_address
        self.env_vars = env_vars or {}
        self._proc: Optional[subprocess.Popen] = None
        threading.Thread(target=self._run, daemon=True,
                         name=f"job-{job_id}").start()

    def _kv_put(self, suffix: str, value: Any) -> None:
        import ray_tpu
        cw = ray_tpu._private.worker.global_worker().core_worker
        cw._gcs.call("kv_put", key=f"{_KV_PREFIX}{self.job_id}::{suffix}",
                     value=json.dumps(value).encode())

    def _set_status(self, status: str, message: str = "") -> None:
        self._kv_put("status", {"status": status, "message": message,
                                "ts": time.time()})

    def _run(self) -> None:
        import tempfile
        self._set_status(RUNNING)
        log_path = os.path.join(tempfile.gettempdir(),
                                f"ray_tpu_job_{self.job_id}.log")
        env = dict(os.environ)
        env["RAY_TPU_ADDRESS"] = self.gcs_address
        env.update(self.env_vars)
        try:
            with open(log_path, "wb") as log:
                self._proc = subprocess.Popen(
                    self.entrypoint, shell=True, stdout=log,
                    stderr=subprocess.STDOUT, env=env,
                    cwd=self.working_dir or None)
                rc = self._proc.wait()
            with open(log_path, "rb") as f:
                logs = f.read()[-200_000:].decode(errors="replace")
            self._kv_put("logs", logs)
            self._set_status(SUCCEEDED if rc == 0 else FAILED,
                             f"exit code {rc}")
        except Exception as e:  # noqa: BLE001
            self._set_status(FAILED, repr(e))

    def ping(self) -> str:
        return "pong"

    def stop_job(self) -> None:
        if self._proc is not None and self._proc.poll() is None:
            self._proc.terminate()


class JobSubmissionClient:
    """reference dashboard/modules/job/sdk.py, over the core API instead
    of REST (the HTTP surface can front this 1:1)."""

    def __init__(self, address: str):
        import ray_tpu
        ray_tpu.init(address, ignore_reinit_error=True)
        self._rt = ray_tpu
        self._address = address

    def _gcs(self):
        return self._rt._private.worker.global_worker().core_worker._gcs

    def submit_job(self, *, entrypoint: str,
                   working_dir: Optional[str] = None,
                   env_vars: Optional[Dict[str, str]] = None) -> str:
        job_id = f"job_{uuid.uuid4().hex[:10]}"
        self._gcs().call(
            "kv_put", key=f"{_KV_PREFIX}{job_id}::meta",
            value=json.dumps({"entrypoint": entrypoint,
                              "submitted_at": time.time()}).encode())
        self._gcs().call(
            "kv_put", key=f"{_KV_PREFIX}{job_id}::status",
            value=json.dumps({"status": PENDING}).encode())
        cls = self._rt.remote(JobSupervisor)
        supervisor = cls.options(
            name=f"JOB_SUPERVISOR::{job_id}", namespace="job",
            num_cpus=0.1).remote(job_id, entrypoint, working_dir,
                                 self._address, env_vars)
        self._rt.get(supervisor.ping.remote(), timeout=120)
        return job_id

    def _kv_get(self, job_id: str, suffix: str) -> Optional[Any]:
        raw = self._gcs().call("kv_get",
                               key=f"{_KV_PREFIX}{job_id}::{suffix}")
        return json.loads(raw) if raw else None

    def get_job_status(self, job_id: str) -> str:
        st = self._kv_get(job_id, "status")
        return st["status"] if st else "NOT_FOUND"

    def get_job_logs(self, job_id: str) -> str:
        return self._kv_get(job_id, "logs") or ""

    def list_jobs(self) -> List[Dict[str, Any]]:
        keys = self._gcs().call("kv_keys", prefix=_KV_PREFIX)
        out = []
        for key in keys:
            if not key.endswith("::meta"):
                continue
            job_id = key[len(_KV_PREFIX):-len("::meta")]
            meta = self._kv_get(job_id, "meta") or {}
            out.append({"job_id": job_id,
                        "status": self.get_job_status(job_id),
                        "entrypoint": meta.get("entrypoint", "")})
        return out

    def wait(self, job_id: str, timeout: float = 600.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in TERMINAL:
                return status
            time.sleep(0.5)
        return self.get_job_status(job_id)

    def stop_job(self, job_id: str) -> None:
        try:
            sup = self._rt.get_actor(f"JOB_SUPERVISOR::{job_id}",
                                     namespace="job")
            self._rt.get(sup.stop_job.remote(), timeout=60)
        except Exception:  # noqa: BLE001 - supervisor already gone; nothing to stop
            pass
