"""Job submission: run driver scripts on the cluster with tracked status.

reference parity: dashboard/modules/job/ — job_manager.py (drivers run as
child processes of an agent-managed supervisor actor), sdk.py
(JobSubmissionClient with submit/status/logs), cli.py. Here the
supervisor is a detached-ish named actor per job; status and logs
persist in the GCS KV so any client can query them.
"""

from ray_tpu.job.manager import JobSubmissionClient, JobSupervisor  # noqa: F401

__all__ = ["JobSubmissionClient", "JobSupervisor"]
