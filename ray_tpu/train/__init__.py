"""ray_tpu.train: distributed training orchestration (Ray Train parity).

reference: python/ray/train — BaseTrainer/DataParallelTrainer +
BackendExecutor + _TrainSession (SURVEY.md §2.3, §3.6), rebuilt with a
jax.distributed/ICI-mesh backend instead of NCCL process groups.
"""

from ray_tpu.train.checkpoint import Checkpoint  # noqa: F401
from ray_tpu.train.config import (CheckpointConfig, FailureConfig,  # noqa: F401
                                  RunConfig, ScalingConfig)
from ray_tpu.train.data_parallel_trainer import (DataParallelTrainer,  # noqa: F401
                                                 Result)
from ray_tpu.train.jax_backend import JaxConfig  # noqa: F401
from ray_tpu.train.jax_trainer import JaxTrainer  # noqa: F401
from ray_tpu.train.tensorflow_backend import TensorflowConfig  # noqa: F401
from ray_tpu.train.tensorflow_trainer import TensorflowTrainer  # noqa: F401
from ray_tpu.train.accelerate_trainer import AccelerateTrainer  # noqa: F401
from ray_tpu.train.sklearn_trainer import SklearnTrainer  # noqa: F401
from ray_tpu.train.torch_trainer import TorchTrainer  # noqa: F401
from ray_tpu.train.transformers_trainer import (TransformersTrainer,  # noqa: F401,E501
                                                prepare_trainer)
from ray_tpu.train.torch_backend import TorchConfig  # noqa: F401
from ray_tpu.train.session import (TrainContext, get_checkpoint,  # noqa: F401
                                   get_context, get_dataset_shard, report)

__all__ = [
    "Checkpoint", "CheckpointConfig", "FailureConfig", "RunConfig",
    "ScalingConfig", "DataParallelTrainer", "Result", "JaxConfig",
    "JaxTrainer", "TorchTrainer", "TorchConfig", "TensorflowTrainer",
    "TransformersTrainer", "prepare_trainer", "SklearnTrainer",
    "AccelerateTrainer",
    "TensorflowConfig", "TrainContext", "report", "get_checkpoint",
    "get_context", "get_dataset_shard",
]

from ray_tpu._private.usage import record_library_usage as _rlu
_rlu('train')
del _rlu
