"""TorchTrainer: torch train loops over a gloo process group.

reference parity: python/ray/train/torch/torch_trainer.py — a
DataParallelTrainer whose backend wires torch.distributed instead of the
jax coordinator (§8.4 trainer inventory row).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train.torch_backend import TorchConfig


class TorchTrainer(DataParallelTrainer):
    _backend_config_cls = TorchConfig

    def __init__(self,
                 train_loop_per_worker: Callable,
                 *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 torch_config: Optional[TorchConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        super().__init__(
            train_loop_per_worker,
            train_loop_config=train_loop_config,
            backend_config=torch_config or TorchConfig(),
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint)
