"""AccelerateTrainer: HF Accelerate loops with config-file propagation.

reference parity: python/ray/train/huggingface/accelerate/
accelerate_trainer.py:44-110 — beyond TorchTrainer it (1) loads and
parses an Accelerate configuration (path from `accelerate config`, a
dict, or the default config location) ONCE on the driver, (2) ships the
raw contents to every worker and materializes them there (including a
nested DeepSpeed json referenced by `deepspeed_config_file`), pointing
`ACCELERATE_CONFIG_FILE` at the materialized copy so `Accelerator()`
picks it up, and (3) strips the topology keys the gang already owns
(num_processes / machine_rank / main_process_ip ... come from the
torch process group env the backend wired), mirroring the reference's
"ignored and automatically set" list.

TPU-first note: as with TransformersTrainer this exists for torch-side
parity — TPU training's first-class path is JaxTrainer.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional, Tuple

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.torch_backend import TorchConfig
from ray_tpu.train.torch_trainer import TorchTrainer

# Accelerate config keys the gang topology owns (reference
# accelerate_trainer.py "will be ignored and automatically set"):
_TOPOLOGY_KEYS = (
    "num_machines", "num_processes", "machine_rank", "gpu_ids",
    "num_cpu_threads_per_process", "main_process_ip",
    "main_process_port", "same_network", "cpu", "use_cpu",
    "rdzv_backend", "main_training_function",
)


def _load_accelerate_config(accelerate_config
                            ) -> Tuple[Optional[str], Optional[str]]:
    """Driver-side load (reference _accelerate_utils.load_accelerate_config):
    returns (config_yaml_raw, deepspeed_json_raw)."""
    if accelerate_config is None:
        # default location as defined by Accelerate, if one exists
        try:
            from accelerate.commands.config import default_config_file
            if os.path.exists(default_config_file):
                accelerate_config = default_config_file
            else:
                return None, None
        except ImportError:
            return None, None
    # yaml only becomes a requirement once a config actually loads
    import yaml
    if isinstance(accelerate_config, dict):
        cfg = dict(accelerate_config)
    else:
        with open(os.fspath(accelerate_config)) as f:
            cfg = yaml.safe_load(f) or {}
    ds_raw = None
    ds_cfg = cfg.get("deepspeed_config")
    if isinstance(ds_cfg, dict) and ds_cfg.get("deepspeed_config_file"):
        # nested DeepSpeed json also ships by value (the path is only
        # meaningful on the driver's filesystem)
        with open(ds_cfg["deepspeed_config_file"]) as f:
            ds_raw = f.read()
    return yaml.safe_dump(cfg), ds_raw


def _apply_accelerate_config_on_worker(config_raw: Optional[str],
                                       deepspeed_raw: Optional[str]
                                       ) -> None:
    """Materialize the shipped config on this worker and point
    ACCELERATE_CONFIG_FILE at it; topology keys are dropped so
    Accelerate reads them from the process-group env instead."""
    import tempfile

    import yaml

    if config_raw is None:
        return
    cfg = yaml.safe_load(config_raw) or {}
    for key in _TOPOLOGY_KEYS:
        cfg.pop(key, None)
    tmpdir = tempfile.mkdtemp(prefix="accelerate_cfg_")
    if deepspeed_raw is not None and isinstance(
            cfg.get("deepspeed_config"), dict):
        ds_path = os.path.join(tmpdir, "deepspeed_config.json")
        with open(ds_path, "w") as f:
            f.write(deepspeed_raw)
        cfg["deepspeed_config"]["deepspeed_config_file"] = ds_path
    path = os.path.join(tmpdir, "accelerate_config.yaml")
    with open(path, "w") as f:
        yaml.safe_dump(cfg, f)
    os.environ["ACCELERATE_CONFIG_FILE"] = path


class AccelerateTrainer(TorchTrainer):
    """TorchTrainer + Accelerate config loading/propagation. The user
    `train_loop_per_worker` constructs `accelerate.Accelerator()` as it
    would outside Ray; the torch process group and the materialized
    config file are already in place on every worker."""

    def __init__(self,
                 train_loop_per_worker: Callable,
                 *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 accelerate_config=None,
                 torch_config: Optional[TorchConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        config_raw, ds_raw = _load_accelerate_config(accelerate_config)

        def wrapped(config=None, _loop=train_loop_per_worker,
                    _raw=config_raw, _ds=ds_raw):
            _apply_accelerate_config_on_worker(_raw, _ds)
            if config is None:
                return _loop()
            return _loop(config)

        super().__init__(
            wrapped,
            train_loop_config=train_loop_config,
            torch_config=torch_config,
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint)
