"""AccelerateTrainer: HF Accelerate train loops over the worker gang.

reference parity: python/ray/train/huggingface/accelerate —
AccelerateTrainer runs a user `train_loop_per_worker` that constructs
`accelerate.Accelerator()` inside an already-wired torch process group
(the Ray side provides RANK/WORLD_SIZE/MASTER_ADDR and the gloo/nccl
group; Accelerate detects the environment and handles device placement
+ DDP wrapping + gradient accumulation). Here the torch backend wires
gloo and the same env, so unmodified Accelerate loops run on the gang.
TPU-first note: as with TransformersTrainer this exists for torch-side
parity — TPU training's first-class path is JaxTrainer.
"""

from __future__ import annotations

from ray_tpu.train.torch_trainer import TorchTrainer


class AccelerateTrainer(TorchTrainer):
    """Exactly TorchTrainer (as in the reference): the
    `train_loop_per_worker(config)` builds its own Accelerator inside
    the torch process group the backend established; Accelerate detects
    the distributed env and handles device placement/DDP/grad
    accumulation itself."""
