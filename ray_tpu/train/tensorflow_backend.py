"""TensorFlow backend: TF_CONFIG wiring for TensorflowTrainer.

reference parity: python/ray/train/tensorflow/config.py —
_TensorflowBackend.on_start gathers every worker's (ip, port) and writes
the MultiWorkerMirroredStrategy TF_CONFIG env var on each worker:
{"cluster": {"worker": [addr0, addr1, ...]}, "task": {"type": "worker",
"index": rank}}. tf.distribute reads it at strategy construction.
"""

from __future__ import annotations

import dataclasses
import json
import logging
from typing import List, Type

from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.jax_backend import _get_node_ip
from ray_tpu.train.worker_group import WorkerGroup

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class TensorflowConfig(BackendConfig):
    @property
    def backend_cls(self) -> Type["Backend"]:
        return _TensorflowBackend


def _get_ip_and_port() -> str:
    from ray_tpu._private.rpc import find_free_port
    return f"{_get_node_ip()}:{find_free_port()}"


def _set_tf_config(addresses: List[str], rank: int) -> None:
    import os
    os.environ["TF_CONFIG"] = json.dumps({
        "cluster": {"worker": addresses},
        "task": {"type": "worker", "index": rank},
    })


class _TensorflowBackend(Backend):
    def on_start(self, worker_group: WorkerGroup,
                 backend_config: TensorflowConfig) -> None:
        import ray_tpu
        addresses = ray_tpu.get(
            [w.apply.remote(_get_ip_and_port)
             for w in worker_group.workers], timeout=120)
        ray_tpu.get([
            w.apply.remote(_set_tf_config, addresses, rank)
            for rank, w in enumerate(worker_group.workers)
        ], timeout=120)
