"""Train/AIR config dataclasses.

reference parity: python/ray/air/config.py — ScalingConfig (:101),
FailureConfig (:377), CheckpointConfig (:428), RunConfig (:577).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional, Tuple, Union


@dataclasses.dataclass
class ScalingConfig:
    """How many workers and what resources each holds (reference
    air/config.py:101). For TPU workers set
    ``resources_per_worker={"TPU": 4}`` and ``use_tpu=True``; the trainer
    gang-schedules one worker per TPU-VM host of the slice."""

    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    trainer_resources: Optional[Dict[str, float]] = None
    # Elastic membership (TorchElastic/Elastic Horovod semantics):
    # setting elastic_min_workers turns the gang elastic — on worker
    # death / node drain the run checkpoints and re-forms at any world
    # size in [elastic_min_workers, elastic_max_workers or
    # num_workers], resharding state over the new mesh, and grows back
    # toward the max when replacement capacity arrives (autoscaler v2
    # lifecycle events / a schedulable replacement probe). None keeps
    # the classic fixed-size gang.
    elastic_min_workers: Optional[int] = None
    elastic_max_workers: Optional[int] = None
    # How long a re-form may wait for bundles to schedule before either
    # proceeding at a smaller feasible world size (>= min) or raising
    # TrainingWorkerError naming the infeasible demand.
    elastic_reform_timeout_s: float = 60.0
    # Collective-wedge watchdog (train/heartbeat.py): max seconds one
    # training round (report->report) may take before the supervisor
    # checks rank heartbeats and, if any are stale, hard-kills the
    # wedged ranks and re-forms the gang (reason="wedge"). None (the
    # default) auto-calibrates as k x the trailing p99 of observed
    # round times — slow-but-alive steps never false-trip, and a cold
    # gang with no timing history has no deadline at all. Runtime-
    # tunable via the GCS metrics_configure(step_deadline_s=...) RPC.
    # Enforced only for elastic gangs (the recovery IS the elastic
    # re-form path).
    step_deadline_s: Optional[float] = None

    def __post_init__(self):
        if self.step_deadline_s is not None and self.step_deadline_s <= 0:
            raise ValueError(
                f"step_deadline_s must be > 0, got {self.step_deadline_s}")
        if self.elastic_max_workers is not None and \
                self.elastic_min_workers is None:
            raise ValueError(
                "elastic_max_workers requires elastic_min_workers")
        if self.elastic_min_workers is not None:
            if self.elastic_min_workers < 1:
                raise ValueError("elastic_min_workers must be >= 1")
            if self.elastic_min_workers > self.num_workers:
                raise ValueError(
                    f"elastic_min_workers={self.elastic_min_workers} > "
                    f"num_workers={self.num_workers}")
            if self.elastic_max_workers is not None and \
                    self.elastic_max_workers < self.num_workers:
                raise ValueError(
                    f"elastic_max_workers={self.elastic_max_workers} < "
                    f"num_workers={self.num_workers}")

    @property
    def elastic(self) -> bool:
        return self.elastic_min_workers is not None

    @property
    def elastic_target_workers(self) -> int:
        """The world size an elastic gang grows toward."""
        return self.elastic_max_workers or self.num_workers

    @property
    def _resources_per_worker_not_none(self) -> Dict[str, float]:
        if self.resources_per_worker is not None:
            return dict(self.resources_per_worker)
        return {"CPU": 1, "TPU": 4} if self.use_tpu else {"CPU": 1}

    def as_placement_group_factory(self) -> List[Dict[str, float]]:
        """Bundle list for the worker gang (reference
        ScalingConfig.as_placement_group_factory)."""
        return [self._resources_per_worker_not_none
                for _ in range(self.num_workers)]

    @property
    def num_tpus_per_worker(self) -> float:
        return self._resources_per_worker_not_none.get("TPU", 0)


@dataclasses.dataclass
class FailureConfig:
    """reference air/config.py:377. max_failures: retries of the whole
    worker group from the last checkpoint; -1 = infinite."""

    max_failures: int = 0


@dataclasses.dataclass
class CheckpointConfig:
    """reference air/config.py:428."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0
    checkpoint_at_end: Optional[bool] = None

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be max|min")
        if self.num_to_keep is not None and self.num_to_keep <= 0:
            raise ValueError("num_to_keep must be positive or None")


@dataclasses.dataclass
class RunConfig:
    """reference air/config.py:577."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None
    verbose: int = 1

    def __post_init__(self):
        if self.storage_path is None:
            self.storage_path = os.path.expanduser("~/ray_tpu_results")
        if self.failure_config is None:
            self.failure_config = FailureConfig()
        if self.checkpoint_config is None:
            self.checkpoint_config = CheckpointConfig()
