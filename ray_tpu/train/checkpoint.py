"""Checkpoint: a directory-of-files abstraction.

reference parity: python/ray/train/_checkpoint.py:55 — Checkpoint with
from_directory/to_directory/as_directory over a storage URI. Storage here
is a filesystem path (local or NFS); jax pytrees ride orbax inside the
directory when the caller uses JaxTrainer's save helpers.
"""

from __future__ import annotations

import contextlib
import os
import shutil
import tempfile
import uuid
from typing import Any, Dict, Iterator, Optional


class Checkpoint:
    """A reference to a directory containing a checkpoint."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise ValueError(f"not a directory: {path}")
        return cls(path)

    def to_directory(self, path: Optional[str] = None) -> str:
        """Materialize into `path` (copy); returns the path."""
        dest = path or os.path.join(
            tempfile.gettempdir(), f"ckpt_{uuid.uuid4().hex[:8]}")
        if os.path.abspath(dest) == self.path:
            return self.path
        os.makedirs(dest, exist_ok=True)
        shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    @contextlib.contextmanager
    def as_directory(self) -> Iterator[str]:
        """Context manager view; local paths are yielded directly without
        copying (reference _checkpoint.py as_directory fast path)."""
        yield self.path

    # -- convenience for jax pytrees ---------------------------------
    def save_pytree(self, tree: Any, name: str = "state") -> None:
        """Write a jax pytree via orbax into this checkpoint dir."""
        import jax
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        dest = os.path.join(self.path, name)
        if os.path.exists(dest):
            shutil.rmtree(dest)
        ckptr.save(dest, jax.device_get(tree))
        ckptr.wait_until_finished()

    def load_pytree(self, name: str = "state",
                    target: Optional[Any] = None) -> Any:
        import jax
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        src = os.path.join(self.path, name)
        if target is not None:
            shapes = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), target)
            return ckptr.restore(src, shapes)
        return ckptr.restore(src)

    def update_metadata(self, metadata: Dict[str, Any]) -> None:
        import json
        with open(os.path.join(self.path, ".metadata.json"), "w") as f:
            json.dump(metadata, f)

    def get_metadata(self) -> Dict[str, Any]:
        import json
        p = os.path.join(self.path, ".metadata.json")
        if not os.path.exists(p):
            return {}
        with open(p) as f:
            return json.load(f)

    def __repr__(self) -> str:
        return f"Checkpoint(path={self.path!r})"
