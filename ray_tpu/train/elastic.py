"""Elastic reconfiguration plane: shared bookkeeping for gang re-forms.

Used by train/backend_executor.py (JaxTrainer / DataParallelTrainer
gangs) and rllib/core/learner_group.py (mesh learner gangs). One
reconfiguration = the span sequence

    elastic.detect -> elastic.drain -> elastic.checkpoint ->
    elastic.reform -> elastic.reshard -> elastic.resume

recorded on the driver's flight-recorder ring (so `ray_tpu timeline
--spans` shows the full cost breakdown and tools/perf_report.py
attributes it into the `elastic_reconfig` bucket), plus

    ray_tpu_elastic_reconfigurations_total{reason}   counter
    ray_tpu_elastic_reconfig_seconds                 histogram

on the cluster metrics plane. While a reconfiguration is in flight the
tracker's phase + age ride every metrics harvest as the "elastic"
snapshot extra; the GCS watchdog's `elastic_stuck_reconfig` probe
alerts when one has been stuck past Config.watchdog_elastic_reconfig_s
(a gang that can neither re-form nor fail is the worst failure mode —
it looks exactly like training, minus the progress).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu._private import spans

# every reconfiguration walks these phases in order
PHASES = ("detect", "drain", "checkpoint", "reform", "reshard", "resume")


def free_port() -> int:
    """A fresh OS-assigned port for a gang coordinator rendezvous
    (shared by the train and learner gang planes; each formation picks
    a new one so re-forms never collide with a TIME_WAIT socket)."""
    from ray_tpu._private.rpc import find_free_port
    return find_free_port()


def gang_runtime_env(key: str) -> Dict[str, Any]:
    """Runtime env for one gang formation's fresh worker processes.

    jax.distributed must initialize before any other jax use in the
    process, which reused pool workers cannot guarantee — the unique
    value under `key` gives each formation its own worker-pool bucket.
    One host CPU device per gang process: the virtual-device test flag
    (--xla_force_host_platform_device_count=8) would otherwise leak in
    and force per-process shard sizes to be divisible by 8; any other
    XLA_FLAGS the operator set (TPU tuning flags etc.) are preserved.
    Shared by the train gang (jax_backend) and the learner gang
    (rllib/core/learner_group)."""
    import os
    import re
    import uuid
    flags = re.sub(r"--xla_force_host_platform_device_count=\S+", "",
                   os.environ.get("XLA_FLAGS", "")).strip()
    return {"env_vars": {
        key: uuid.uuid4().hex,
        "XLA_FLAGS": (flags + " "
                      "--xla_force_host_platform_device_count=1").strip(),
    }}


def _metrics():
    from ray_tpu.util.metrics import Counter, Histogram, get_or_create
    counter = get_or_create(
        Counter, "ray_tpu_elastic_reconfigurations_total",
        description="completed elastic gang reconfigurations",
        tag_keys=("reason",))
    hist = get_or_create(
        Histogram, "ray_tpu_elastic_reconfig_seconds",
        description="wall time of one elastic reconfiguration "
                    "(detect through resume)",
        boundaries=[0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0])
    return counter, hist


class ReconfigTracker:
    """Phase/metrics/span bookkeeping for ONE gang's reconfigurations.

    Usage:
        rec = tracker.start(reason="worker_death", world_size=4)
        with rec.phase("drain"):
            ...
        ...
        rec.finish(world_size=3)        # success: metrics + history
        # or rec.abort(error)           # failure: state cleared, no count

    The tracker registers itself as an `elastic:*` metrics snapshot
    extra under a per-INSTANCE key so in-flight phase + age are visible
    to the watchdog: two same-named gangs in one driver (e.g. two
    concurrent fit() calls) each stay visible, and one tracker's
    close() can never deregister the other.
    """

    def __init__(self, name: str = "train"):
        import uuid
        self.name = name
        self._extra_key = f"elastic:{name}:{uuid.uuid4().hex[:8]}"
        self._lock = threading.Lock()
        self._counter, self._hist = _metrics()
        self.reconfigs_total = 0
        self.history: List[Dict[str, Any]] = []
        self._current: Optional[Dict[str, Any]] = None
        from ray_tpu._private import metrics_plane
        metrics_plane.register_snapshot_extra(
            self._extra_key, self.snapshot)

    def close(self) -> None:
        from ray_tpu._private import metrics_plane
        metrics_plane.unregister_snapshot_extra(self._extra_key)

    # ---- one reconfiguration ----------------------------------------
    def start(self, reason: str, world_size: int) -> "_Reconfig":
        rec = _Reconfig(self, reason, world_size)
        with self._lock:
            self._current = rec.state
        return rec

    def _finished(self, rec: "_Reconfig", ok: bool) -> None:
        with self._lock:
            if self._current is rec.state:
                self._current = None
            if ok:
                self.reconfigs_total += 1
                self.history.append({
                    "reason": rec.reason,
                    "from_world_size": rec.from_world,
                    "to_world_size": rec.to_world,
                    "duration_s": round(rec.duration_s, 3),
                    "phases_s": {k: round(v, 3)
                                 for k, v in rec.phase_seconds.items()},
                    "ts": time.time(),
                })
                del self.history[:-64]
        if ok:
            self._counter.inc(tags={"reason": rec.reason})
            self._hist.observe(rec.duration_s)

    # ---- watchdog-facing snapshot -----------------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            cur = self._current
            out: Dict[str, Any] = {
                "gang": self.name,
                "reconfigs_total": self.reconfigs_total,
                "in_progress": cur is not None,
            }
            if cur is not None:
                out["reason"] = cur["reason"]
                out["phase"] = cur["phase"]
                out["age_s"] = round(
                    time.monotonic() - cur["started_mono"], 3)
            return out


class _Reconfig:
    def __init__(self, tracker: ReconfigTracker, reason: str,
                 world_size: int):
        self.tracker = tracker
        self.reason = reason
        self.from_world = world_size
        self.to_world: Optional[int] = None
        self._t0 = time.monotonic()
        self.duration_s = 0.0
        self.phase_seconds: Dict[str, float] = {}
        self.state: Dict[str, Any] = {
            "reason": reason, "phase": "detect",
            "started_mono": self._t0,
        }
        # goodput: the whole detect->resume window is badput on the
        # driver's ledger — wedge recoveries get their own bucket so
        # churn and hangs stay distinguishable in the ledger
        from ray_tpu._private import goodput
        self._goodput_token = goodput.enter(
            "wedge_recovery" if reason == "wedge"
            else "elastic_reconfig")
        spans.instant("elastic.detect", reason=reason,
                      gang=tracker.name, world_size=world_size)

    def phase(self, name: str, **attrs: Any):
        """Span-recording context manager for one phase; also updates
        the watchdog-visible state."""
        assert name in PHASES, name
        self.state["phase"] = name
        return _Phase(self, name, attrs)

    def finish(self, world_size: int) -> None:
        from ray_tpu._private import goodput
        goodput.exit(self._goodput_token)
        self._goodput_token = None
        self.to_world = world_size
        self.duration_s = time.monotonic() - self._t0
        spans.instant("elastic.resumed", reason=self.reason,
                      gang=self.tracker.name, world_size=world_size,
                      duration_s=round(self.duration_s, 3))
        self.tracker._finished(self, ok=True)

    def abort(self, error: Optional[BaseException] = None) -> None:
        from ray_tpu._private import goodput
        goodput.exit(self._goodput_token)
        self._goodput_token = None
        self.duration_s = time.monotonic() - self._t0
        spans.instant("elastic.aborted", reason=self.reason,
                      gang=self.tracker.name,
                      error=repr(error) if error else "")
        self.tracker._finished(self, ok=False)


class _Phase:
    def __init__(self, rec: _Reconfig, name: str, attrs: Dict[str, Any]):
        self.rec = rec
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        self._t0 = time.monotonic()
        self._sp = spans.start_span(
            f"elastic.{self.name}", reason=self.rec.reason,
            gang=self.rec.tracker.name, **self.attrs)
        return self._sp.attrs if self._sp is not None else {}

    def __exit__(self, exc_type, exc, tb):
        spans.finish_span(self._sp)
        self.rec.phase_seconds[self.name] = \
            self.rec.phase_seconds.get(self.name, 0.0) \
            + (time.monotonic() - self._t0)
        return False


class MembershipWatch:
    """Driver-side subscription to gang-membership signals: autoscaler
    v2 lifecycle events ("autoscaler_lifecycle" pubsub) and GCS node
    ALIVE/DEAD pushes ("node" pubsub). Callbacks only set flags — the
    reconfiguration itself runs on the training driver thread at the
    next step boundary (reconfiguring from inside a pubsub callback
    would race the result loop)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._tokens: List[tuple] = []
        self._capacity_event = False
        self._lost_nodes: List[str] = []
        self._watch_nodes: frozenset = frozenset()

    def subscribe(self) -> None:
        cw = _core_worker_or_none()
        if cw is None:
            return
        # record tokens one at a time: if the SECOND subscribe fails,
        # the first must stay tracked so unsubscribe() can still tear
        # it down (a discarded token leaves the GCS pushing lifecycle
        # events to this driver forever)
        for channel, cb in (("autoscaler_lifecycle", self._on_lifecycle),
                            ("node", self._on_node)):
            try:
                self._tokens.append((channel, cw.subscribe(channel, cb)))
            except Exception:  # noqa: BLE001 - no GCS (unit tests): the
                # reconfig loop still works off probe polling + failures
                break

    def unsubscribe(self) -> None:
        cw = _core_worker_or_none()
        for channel, token in self._tokens:
            try:
                if cw is not None:
                    cw.unsubscribe(channel, token)
            except Exception:  # noqa: BLE001 - GCS gone; sub dies with it
                pass
        self._tokens = []

    def watch_nodes(self, node_ids: List[str]) -> None:
        """The node set whose death means 'a gang member's host is
        gone' (set after every formation)."""
        with self._lock:
            self._watch_nodes = frozenset(node_ids)

    # ---- pubsub callbacks -------------------------------------------
    def _on_lifecycle(self, evt: Any) -> None:
        try:
            to = evt.get("to")
        except Exception:  # noqa: BLE001 - foreign message shape
            return
        if to == "RAY_RUNNING":
            with self._lock:
                self._capacity_event = True

    def _on_node(self, msg: Any) -> None:
        try:
            kind, info = msg
            node_id = info.node_id.hex()
        except Exception:  # noqa: BLE001 - foreign message shape
            return
        with self._lock:
            if kind == "ALIVE":
                self._capacity_event = True
            elif kind == "DEAD" and node_id in self._watch_nodes:
                self._lost_nodes.append(node_id)

    # ---- driver-side polls ------------------------------------------
    def take_capacity_event(self) -> bool:
        with self._lock:
            hit, self._capacity_event = self._capacity_event, False
            return hit

    def take_lost_nodes(self) -> List[str]:
        with self._lock:
            lost, self._lost_nodes = self._lost_nodes, []
            return lost


def _core_worker_or_none():
    from ray_tpu._private import worker as worker_mod
    w = worker_mod.global_worker_or_none()
    return None if w is None else w.core_worker
