"""JaxTrainer: the flagship trainer (BASELINE.json north star).

reference parity: slots into the trainer inventory exactly where
TorchTrainer does (python/ray/train/torch/torch_trainer.py over
DataParallelTrainer, SURVEY.md §8.4) — a DataParallelTrainer subclass
whose backend wires jax.distributed over the gang instead of NCCL.

The per-worker loop is plain jax: build a Mesh (which spans the whole
slice once jax.distributed is initialized), make_train_step over it,
report() metrics/checkpoints. See tests/test_train.py for the canonical
loop shape.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train.jax_backend import JaxConfig


class JaxTrainer(DataParallelTrainer):
    _backend_config_cls = JaxConfig

    def __init__(self,
                 train_loop_per_worker: Callable,
                 *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 jax_config: Optional[JaxConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        super().__init__(
            train_loop_per_worker,
            train_loop_config=train_loop_config,
            backend_config=jax_config or JaxConfig(),
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint)
