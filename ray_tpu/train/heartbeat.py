"""Gang heartbeat plane: rank liveness + step deadlines (ISSUE 17).

A rank that wedges *inside* an XLA collective — SIGSTOP'd, GIL-stalled,
or spinning on a partitioned DCN link — blocks every other rank forever
while looking exactly like a long step from the driver. The membership
plane (GCS node/lifecycle pubsub) never fires because nothing died.

The detection loop this module powers:

- **HeartbeatSender** (worker side): a sidecar daemon thread that stamps
  ``(step, phase, monotonic receipt)`` into the GCS ``gang_heartbeat``
  table on a short period. It owns its OWN RpcClient — the core worker's
  client is lock-serialized behind the main thread, which is exactly the
  thread that is stuck in the collective. A SIGSTOP freezes every thread
  including this one, so a *stale* heartbeat (not a dead connection) is
  the wedge signal.
- **StepDeadline** (driver side): per-step deadline, either explicit
  (``ScalingConfig.step_deadline_s``) or auto-calibrated as
  ``k x trailing-p99`` of observed step times so slow-but-alive steps
  never false-trip. Runtime-tunable: ``metrics_configure(
  step_deadline_s=...)`` plants an override the GCS hands back with
  every heartbeat query.
- **classify_wedge / hard_kill_ranks** (driver side): slice-aware
  classification (every rank of one node wedging reads as a slice
  leave, not N independent failures) and the hard-kill actuator. A
  SIGSTOP'd rank cannot run cleanup and the normal ``ray_tpu.kill``
  path RPCs the victim (``cw_kill_self``) — which hangs on a stopped
  process — so the kill goes to the victim's *node manager* instead
  (``nm_kill_worker_pid``: postmortem capture + SIGKILL, which Linux
  delivers to stopped processes).

The trip condition is deliberately two-factor: the step deadline must
have expired AND at least one rank's heartbeat must be stale. A slow
step with every rank still beating keeps waiting; a stale rank before
the deadline is merely suspicious (the gauge + watchdog probe surface
it) but does not tear the gang down.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

# Sidecar beat cadence. Staleness is judged against
# Config.watchdog_gang_heartbeat_s (default 10s = ~20 missed beats), so
# one chaos-delayed or GC-paused beat never reads as a wedge.
HEARTBEAT_PERIOD_S = 0.5

# Auto-calibrated deadline: k x trailing p99 of observed step time,
# floored so microbenchmark-fast steps don't produce a hair-trigger
# deadline, and armed only after MIN_SAMPLES observations (a cold gang
# has no distribution to calibrate against — no deadline, no trip).
DEADLINE_K = 4.0
DEADLINE_FLOOR_S = 5.0
DEADLINE_MIN_SAMPLES = 3
DEADLINE_WINDOW = 64


class HeartbeatSender:
    """Worker-side sidecar: beats ``gang_heartbeat`` into the GCS.

    Runs on its own daemon thread with its own RpcClient; the send is a
    oneway (fire-and-forget) so a slow GCS never backs the sidecar up.
    Failures are swallowed and retried next beat — a missing heartbeat
    IS the signal the supervisor consumes, never an exception here.
    """

    def __init__(self, gang: str, rank: int,
                 period_s: float = HEARTBEAT_PERIOD_S):
        self.gang = gang
        self.rank = int(rank)
        self.period_s = float(period_s)
        self._step = 0
        self._phase = "init"
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._client = None

    # -- main-thread surface (called from the train loop / actor) ------

    def note_step(self, step: Optional[int] = None) -> None:
        self._step = self._step + 1 if step is None else int(step)

    def set_phase(self, phase: str) -> None:
        self._phase = phase

    def start(self) -> bool:
        """Resolve the GCS address from this process's core worker and
        start beating. Returns False (and stays inert) outside a
        connected worker process — heartbeats are best-effort
        observability, never a formation hard-dependency."""
        addr = _gcs_address_or_none()
        if addr is None:
            logger.debug("heartbeat sender for gang %s rank %d: no core "
                         "worker in this process; not starting",
                         self.gang, self.rank)
            return False
        from ray_tpu._private.rpc import RpcClient
        self._client = RpcClient(addr, timeout=5)
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"gang-heartbeat-{self.gang}-r{self.rank}")
        self._thread.start()
        return True

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        if self._client is not None:
            try:
                self._client.close()
            except Exception:  # noqa: BLE001 - teardown; socket may be gone
                pass
            self._client = None

    # -- sidecar thread ------------------------------------------------

    def _run(self) -> None:
        node_id = _node_id_or_empty()
        pid = os.getpid()
        while not self._stop.is_set():
            try:
                self._client.send_oneway(
                    "gang_heartbeat", gang=self.gang, rank=self.rank,
                    step=self._step, phase=self._phase,
                    node_id=node_id, pid=pid)
            except Exception:  # noqa: BLE001 - a missed beat IS the signal
                pass
            self._stop.wait(self.period_s)


def _gcs_address_or_none() -> Optional[Tuple[str, int]]:
    try:
        from ray_tpu._private import worker as worker_mod
        w = worker_mod.global_worker_or_none()
        if w is None or w.core_worker is None:
            return None
        return tuple(w.core_worker.gcs_address)
    except Exception:  # noqa: BLE001 - torn-down worker: stay inert
        return None


def _node_id_or_empty() -> str:
    """This process's node id hex — the GCS node-table key, which is
    what lets gang_heartbeats enrich the record with the NM address
    the hard-kill actuator routes through."""
    try:
        from ray_tpu._private import worker as worker_mod
        w = worker_mod.global_worker_or_none()
        if w is None or w.core_worker is None:
            return ""
        return str(getattr(w.core_worker, "node_id_hex", "") or "")
    except Exception:  # noqa: BLE001 - best-effort enrichment
        return ""


class StepDeadline:
    """Per-step deadline: explicit, or k x trailing-p99 auto-calibrated.

    ``current(override_s)`` resolution order (first non-None wins):
    runtime override (metrics_configure, carried back on every
    heartbeat query) > explicit (ScalingConfig.step_deadline_s) >
    auto-calibration. Auto returns None until MIN_SAMPLES step times
    have been observed — no distribution, no deadline, no trip.
    """

    def __init__(self, explicit_s: Optional[float] = None,
                 k: float = DEADLINE_K,
                 floor_s: float = DEADLINE_FLOOR_S,
                 window: int = DEADLINE_WINDOW,
                 min_samples: int = DEADLINE_MIN_SAMPLES):
        if explicit_s is not None and explicit_s <= 0:
            raise ValueError(f"step deadline must be > 0, got {explicit_s}")
        self.explicit_s = explicit_s
        self.k = float(k)
        self.floor_s = float(floor_s)
        self.window = int(window)
        self.min_samples = int(min_samples)
        self._samples: List[float] = []
        self._lock = threading.Lock()

    def observe(self, step_s: float) -> None:
        if step_s < 0:
            return
        with self._lock:
            self._samples.append(float(step_s))
            if len(self._samples) > self.window:
                del self._samples[:len(self._samples) - self.window]

    def current(self, override_s: Optional[float] = None
                ) -> Optional[float]:
        if override_s is not None and override_s > 0:
            return float(override_s)
        if self.explicit_s is not None:
            return self.explicit_s
        with self._lock:
            if len(self._samples) < self.min_samples:
                return None
            ordered = sorted(self._samples)
        p99 = ordered[min(len(ordered) - 1,
                          int(0.99 * (len(ordered) - 1) + 0.999999))]
        return max(self.floor_s, self.k * p99)


# ---------------------------------------------------------------------------
# Driver-side query / classification / kill helpers
# ---------------------------------------------------------------------------


def query_gang(gcs_call, gang: str) -> Dict[str, Any]:
    """One ``gang_heartbeats`` round trip. Returns the raw reply:
    ``{"ranks": {rank: {step, phase, node_id, pid, nm_address, age_s}},
    "step_deadline_override_s": float|None}``. ``gcs_call`` is any
    callable with the RpcClient.call signature (method, **kwargs)."""
    return gcs_call("gang_heartbeats", gang=gang)


def stale_ranks(reply: Dict[str, Any], stale_after_s: float
                ) -> List[Dict[str, Any]]:
    """Ranks whose heartbeat age exceeds the staleness threshold. Each
    record is the GCS reply row plus its rank under ``"rank"`` and the
    reply's gang under ``"gang"`` (the kill actuator stamps both into
    the NM's kill reason)."""
    out = []
    gang = reply.get("gang", "?")
    for rank, rec in sorted((reply.get("ranks") or {}).items()):
        if rec.get("age_s", 0.0) > stale_after_s:
            out.append({"rank": int(rank), "gang": gang, **rec})
    return out


def classify_wedge(reply: Dict[str, Any],
                   stale: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Slice-aware classification of a wedge.

    Groups ranks by node (an ICI slice maps to a host/node in this
    runtime): when every stale rank sits on a node whose ranks are ALL
    stale, the wedge reads as ``slice_leave`` — one membership event,
    not N independent rank failures. Any stale rank on a node with
    fresh siblings makes it ``rank_wedge``.
    """
    ranks = reply.get("ranks") or {}
    stale_set = {r["rank"] for r in stale}
    by_node: Dict[str, List[int]] = {}
    for rank, rec in ranks.items():
        by_node.setdefault(rec.get("node_id") or "", []).append(int(rank))
    wedged_nodes = [node for node, members in by_node.items()
                    if members and all(m in stale_set for m in members)]
    covered = {m for node in wedged_nodes for m in by_node[node]}
    kind = "slice_leave" if stale_set and stale_set <= covered \
        else "rank_wedge"
    return {"kind": kind, "ranks": sorted(stale_set),
            "nodes": sorted(n for n in wedged_nodes if n)}


def hard_kill_ranks(stale: List[Dict[str, Any]],
                    timeout: float = 10.0) -> List[int]:
    """SIGKILL each wedged rank via its node manager.

    NOT ``ray_tpu.kill``: that path RPCs the victim itself
    (``cw_kill_self``), which a SIGSTOP'd process never answers — the
    kill would block for the full RPC timeout per rank. The NM path
    (``nm_kill_worker_pid``) captures a postmortem bundle (1s budget,
    tolerates an unresponsive victim) then SIGKILLs the pid, which the
    kernel delivers to stopped processes. Returns the ranks confirmed
    killed; misses (rank's NM unreachable, pid already gone) are logged
    and skipped — gang teardown sweeps whatever survives.
    """
    from ray_tpu._private.rpc import RpcClient
    killed: List[int] = []
    for rec in stale:
        nm_addr = rec.get("nm_address")
        pid = rec.get("pid")
        if not nm_addr or not pid:
            logger.warning("wedged rank %s has no NM address/pid on its "
                           "heartbeat record; leaving it to gang teardown",
                           rec.get("rank"))
            continue
        client = RpcClient(tuple(nm_addr), timeout=timeout)
        try:
            if client.call("nm_kill_worker_pid", pid=int(pid),
                           reason=f"gang {rec.get('gang', '?')} rank "
                                  f"{rec['rank']} wedged "
                                  f"(heartbeat {rec.get('age_s', 0):.1f}s "
                                  f"stale)"):
                killed.append(rec["rank"])
        except Exception:  # noqa: BLE001 - NM down: node death path owns it
            logger.warning("nm_kill_worker_pid for wedged rank %s "
                           "(pid %s) failed; its node may be dead",
                           rec["rank"], pid, exc_info=True)
        finally:
            try:
                client.close()
            except Exception:  # noqa: BLE001 - best-effort socket close
                pass
    return killed


def clear_gang(gcs_call, gang: str) -> None:
    """Drop a gang's heartbeat rows (teardown): stale rows from a dead
    formation would otherwise export as wedged-forever gauge series."""
    try:
        gcs_call("gang_heartbeat_clear", gang=gang)
    except Exception:  # noqa: BLE001 - GCS gone at shutdown: rows die with it
        pass
