"""JaxBackend: the TPU-native replacement for _TorchBackend.

reference parity: python/ray/train/torch/config.py:22,148-200 —
_TorchBackend.on_start broadcasts rank-0's address and runs
dist.init_process_group(nccl|gloo) on every worker, plus torchelastic env
(:129-145). Here the "process group" is jax's distributed runtime: worker
0 hosts the coordinator, every worker calls jax.distributed.initialize
(coordinator_address, num_processes=world_size, process_id=rank), after
which jax.devices() spans the whole slice and pjit/shard_map collectives
ride ICI. (SURVEY.md §7.1 translation table, row 1.)
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional, Type

from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.worker_group import WorkerGroup

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class JaxConfig(BackendConfig):
    """distributed=None (default): initialize jax.distributed only when
    the gang spans more than one process AND TPU chips are attached —
    single-worker and chip-free CI runs skip the coordinator entirely.

    coordinator_port=0 picks a fresh free port on worker 0's node for
    EVERY gang formation. Elastic gangs always do this — the
    coordinator is re-hosted each re-form while the previous
    formation's port may still sit in TIME_WAIT, so a fixed value is
    ignored there (with a warning)."""

    distributed: Optional[bool] = None
    coordinator_port: int = 8476

    @property
    def backend_cls(self) -> Type["Backend"]:
        return _JaxBackend


def _get_node_ip() -> str:
    import socket
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def _init_jax_distributed(coordinator_address: str, num_processes: int,
                          process_id: int) -> None:
    import os

    import jax
    # Honor an explicit platform pin (the chip-free test ladder sets
    # JAX_PLATFORMS=cpu): device plugins can re-assert themselves over
    # the env var, so pin through jax.config like tests/conftest.py.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)
    if plat == "cpu":
        # XLA's CPU backend refuses cross-process computations unless
        # collectives go through gloo — needed for the chip-free ladder
        # to run real multi-process gang collectives.
        try:
            jax.config.update(
                "jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 - older jax: no such knob
            pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)


from ray_tpu.train.elastic import free_port as _free_port


class _JaxBackend(Backend):
    def gang_env(self, backend_config: JaxConfig,
                 num_workers: int = 1) -> Optional[dict]:
        """Fresh worker processes per gang formation when jax.distributed
        is requested: initialize() must run before any other jax use in
        the process, which reused pool workers cannot guarantee — and an
        elastic re-form (new world size, new coordinator) needs a clean
        runtime in every member. The unique key gives each formation its
        own worker-pool bucket; one host CPU device per process keeps
        chip-free meshes 1 device/rank (the virtual-device test flag
        would otherwise leak in).

        distributed=None (auto) must be treated as POSSIBLY distributed
        for any multi-worker gang: on_start only resolves the TPU probe
        after the workers exist, and a re-form that reuses pool workers
        because gang_env guessed "not distributed" would re-run
        jax.distributed.initialize in a process that already used jax."""
        if backend_config.distributed is False or \
                (backend_config.distributed is None and num_workers <= 1):
            return None
        from ray_tpu.train.elastic import gang_runtime_env
        return gang_runtime_env("RAY_TPU_TRAIN_GANG")

    def on_start(self, worker_group: WorkerGroup,
                 backend_config: JaxConfig) -> None:
        distributed = backend_config.distributed
        if distributed is None:
            # Probe on worker 0, not the driver: the driver may sit on a
            # CPU-only head node while workers hold the TPU slice.
            distributed = len(worker_group) > 1 and \
                worker_group.execute_single(0, _worker_has_tpu)
        if not distributed:
            logger.debug("JaxBackend: single-process mode, no coordinator")
            return
        # Rank 0's node hosts the coordinator (reference
        # torch/config.py:106-112 picks MASTER_ADDR from worker 0).
        ip = worker_group.execute_single(0, _get_node_ip)
        port = backend_config.coordinator_port
        if port and getattr(worker_group, "elastic", False):
            # a re-form re-hosts the coordinator while the previous
            # formation's socket may still sit in TIME_WAIT — a fixed
            # port would fail the reconfiguration with EADDRINUSE and
            # spend FailureConfig budget on a port collision. Only an
            # explicitly pinned (non-default) port is worth a warning.
            if port != JaxConfig.coordinator_port:
                logger.warning(
                    "JaxConfig.coordinator_port=%d ignored for the "
                    "elastic gang: each formation picks a fresh free "
                    "port", port)
            port = 0
        port = port or worker_group.execute_single(0, _free_port)
        coordinator = f"{ip}:{port}"
        import ray_tpu
        ray_tpu.get([
            w.apply.remote(_init_jax_distributed, coordinator,
                              len(worker_group), rank)
            for rank, w in enumerate(worker_group.workers)
        ], timeout=300)


def _worker_has_tpu() -> bool:
    from ray_tpu._private.accelerators.tpu import TPUAcceleratorManager
    return TPUAcceleratorManager.get_current_node_num_accelerators() > 0
