"""TensorflowTrainer: tf train loops with TF_CONFIG wiring.

reference parity: python/ray/train/tensorflow/tensorflow_trainer.py — a
DataParallelTrainer whose backend writes TF_CONFIG for
MultiWorkerMirroredStrategy instead of the jax coordinator (§8.4
trainer inventory row).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train.tensorflow_backend import TensorflowConfig


class TensorflowTrainer(DataParallelTrainer):
    _backend_config_cls = TensorflowConfig

    def __init__(self,
                 train_loop_per_worker: Callable,
                 *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 tensorflow_config: Optional[TensorflowConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        super().__init__(
            train_loop_per_worker,
            train_loop_config=train_loop_config,
            backend_config=tensorflow_config or TensorflowConfig(),
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint)
