"""DataParallelTrainer: SPMD train loops over a worker gang.

reference parity: python/ray/train/data_parallel_trainer.py:26 and
base_trainer.py:74,579 — fit() runs the training loop, spawning a
BackendExecutor (backend_executor.py:65), streaming results, persisting
checkpoints, restarting on failure per FailureConfig. The reference routes
fit() through a single-trial Tune run; here the trial loop is direct (the
Tune-equivalent integrates via the same Trainable contract in
ray_tpu.tune).
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train.backend import BackendConfig
from ray_tpu.train.backend_executor import (BackendExecutor,
                                            TrainingWorkerError)
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.checkpoint_manager import CheckpointManager
from ray_tpu.train.config import RunConfig, ScalingConfig

logger = logging.getLogger(__name__)


@dataclass
class Result:
    """reference parity: python/ray/air/result.py Result."""

    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    error: Optional[BaseException] = None
    path: str = ""
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)

    @property
    def best_checkpoints(self) -> List[Checkpoint]:
        return self._best_checkpoints

    _best_checkpoints: List[Checkpoint] = field(default_factory=list)


class DataParallelTrainer:
    """Runs `train_loop_per_worker` on every rank of the gang."""

    _backend_config_cls = BackendConfig

    def __init__(self,
                 train_loop_per_worker: Callable,
                 *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 backend_config: Optional[BackendConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        self._train_loop = train_loop_per_worker
        self._train_loop_config = train_loop_config
        self._backend_config = backend_config or self._backend_config_cls()
        self._scaling_config = scaling_config or ScalingConfig()
        self._run_config = run_config or RunConfig()
        self._datasets = datasets
        self._resume_from = resume_from_checkpoint

    def fit(self) -> Result:
        run_name = self._run_config.name or \
            f"{type(self).__name__}_{time.strftime('%Y%m%d_%H%M%S')}"
        run_dir = os.path.join(self._run_config.storage_path, run_name)
        os.makedirs(run_dir, exist_ok=True)
        ckpt_mgr = CheckpointManager(
            run_dir, self._run_config.checkpoint_config)

        # goodput ledger for this job, bound to the driving thread:
        # every wall second of fit() lands in exactly one bucket
        # (checkpoint persists, elastic re-forms, and compile charges
        # re-attribute inside the open scopes; the rest is idle)
        from ray_tpu._private import goodput
        goodput.ledger(run_name).bind()

        executor = BackendExecutor(
            self._backend_config, self._scaling_config,
            max_failures=self._run_config.failure_config.max_failures)
        executor.start()

        metrics_history: List[Dict[str, Any]] = []
        last_metrics: Dict[str, Any] = {}
        error: Optional[BaseException] = None
        try:
            executor.start_training(
                self._train_loop, self._train_loop_config,
                checkpoint_dir=(self._resume_from.path
                                if self._resume_from else None),
                experiment_name=run_name, trial_dir=run_dir,
                datasets=self._datasets)
            while True:
                results = executor.get_next_results()
                if results is None:
                    break
                # rank-0 metrics are canonical (reference
                # data_parallel_trainer training_loop: first worker result)
                by_rank = {r.rank: r for r in results}
                r0 = by_rank.get(0, results[0])
                last_metrics = r0.metrics
                metrics_history.append(r0.metrics)
                ckpt_dirs = [r.checkpoint_dir for r in results
                             if r.checkpoint_dir]
                if ckpt_dirs:
                    # all ranks report the same logical checkpoint; rank 0
                    # (or the only reporter) wins. A vanished worker dir
                    # (e.g. HF's save_total_limit rotated it away before
                    # the copy) loses that checkpoint, not the run.
                    try:
                        persisted = ckpt_mgr.register(
                            r0.checkpoint_dir or ckpt_dirs[0],
                            r0.metrics)
                        executor.note_checkpoint(persisted.path)
                    except OSError as ce:
                        logger.warning(
                            "checkpoint dir %s disappeared before "
                            "persisting (%s); continuing",
                            r0.checkpoint_dir or ckpt_dirs[0], ce)
        except TrainingWorkerError as e:
            error = e
        finally:
            executor.shutdown()
            goodput.unbind()

        return Result(
            metrics=last_metrics,
            checkpoint=ckpt_mgr.latest,
            error=error,
            path=run_dir,
            metrics_history=metrics_history,
            _best_checkpoints=ckpt_mgr.list(),
        )

    @classmethod
    def restore(cls, path: str, **kwargs) -> "DataParallelTrainer":
        """Resume from the newest checkpoint under a prior run dir
        (reference base_trainer.py Trainer.restore). Resolution goes
        through the atomic LATEST pointer (checkpoint_manager.py) so an
        interrupted save can never be picked as the resume target."""
        from ray_tpu.train.checkpoint_manager import latest_checkpoint_path
        latest = latest_checkpoint_path(path)
        if latest is None:
            raise ValueError(f"no checkpoints under {path}")
        kwargs.setdefault("resume_from_checkpoint", Checkpoint(latest))
        return cls(**kwargs)
