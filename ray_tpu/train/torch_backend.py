"""Torch backend: gloo process-group bootstrap for TorchTrainer.

reference parity: python/ray/train/torch/config.py:22,148-200 —
_TorchBackend.on_start broadcasts rank-0's address and runs
dist.init_process_group on every worker. On this framework the primary
compute path is jax over ICI (JaxConfig); the torch backend exists for
CPU/gloo workloads and API parity (§8.4 trainer inventory). NCCL is
deliberately absent — no CUDA anywhere in the tree.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Type

from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.jax_backend import _get_node_ip
from ray_tpu.train.worker_group import WorkerGroup

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class TorchConfig(BackendConfig):
    backend: str = "gloo"
    timeout_s: int = 300

    @property
    def backend_cls(self) -> Type["Backend"]:
        return _TorchBackend


def _free_port() -> int:
    from ray_tpu._private.rpc import find_free_port
    return find_free_port()


def _init_process_group(master_addr: str, master_port: int, backend: str,
                        world_size: int, rank: int,
                        timeout_s: int) -> None:
    import datetime
    import os

    import torch.distributed as dist
    os.environ["MASTER_ADDR"] = master_addr
    os.environ["MASTER_PORT"] = str(master_port)
    dist.init_process_group(
        backend=backend,
        init_method=f"tcp://{master_addr}:{master_port}",
        world_size=world_size, rank=rank,
        timeout=datetime.timedelta(seconds=timeout_s))


def _destroy_process_group() -> None:
    import torch.distributed as dist
    if dist.is_initialized():
        dist.destroy_process_group()


class _TorchBackend(Backend):
    def on_start(self, worker_group: WorkerGroup,
                 backend_config: TorchConfig) -> None:
        # world_size=1 still gets a process group so dist.* calls in the
        # user loop work unchanged (reference _TorchBackend does too).
        # rank 0's node hosts the rendezvous (reference
        # torch/config.py:106-112 picks MASTER_ADDR from worker 0)
        ip = worker_group.execute_single(0, _get_node_ip)
        port = worker_group.execute_single(0, _free_port)
        import ray_tpu
        ray_tpu.get([
            w.apply.remote(_init_process_group, ip, port,
                           backend_config.backend, len(worker_group),
                           rank, backend_config.timeout_s)
            for rank, w in enumerate(worker_group.workers)
        ], timeout=backend_config.timeout_s + 60)

    def on_shutdown(self, worker_group: WorkerGroup,
                    backend_config: TorchConfig) -> None:
        import ray_tpu
        try:
            ray_tpu.get([w.apply.remote(_destroy_process_group)
                         for w in worker_group.workers], timeout=60)
        except Exception:  # noqa: BLE001 - workers may already be dead
            pass
