"""TransformersTrainer: HF Transformers fine-tuning over the worker gang.

reference parity: python/ray/train/huggingface/transformers —
TransformersTrainer wraps a `trainer_init_per_worker` returning a
`transformers.Trainer`; the Ray side gangs the workers, wires the torch
process group (gloo here; the reference prepares the same env), injects
a report callback translating HF logs into `ray_tpu.train.report`
calls, and runs `trainer.train()` on every rank. TPU-first note: this
exists for parity with torch-side HF workloads — the first-class path
for transformer training on TPU is JaxTrainer + the in-tree model stack.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train.torch_backend import TorchConfig


def prepare_trainer(trainer):
    """Attach the report callback bridging HF logging to
    ray_tpu.train.report (reference: RayTrainReportCallback).
    Idempotent: TransformersTrainer also calls this automatically, and
    user init functions following the reference pattern call it too —
    the callback must not attach twice (doubled report streams)."""
    from transformers import TrainerCallback

    import ray_tpu.train as train_mod

    class _RayTpuReportCallback(TrainerCallback):
        def on_log(self, args, state, control, logs=None, **kwargs):
            if logs:
                metrics = {k: v for k, v in logs.items()
                           if isinstance(v, (int, float))}
                metrics["step"] = state.global_step
                train_mod.report(metrics)

        def on_save(self, args, state, control, **kwargs):
            # bridge HF checkpoint saves into the session's checkpoint
            # stream (reference RayTrainReportCallback does the same),
            # so fit() returns real checkpoints and resume works
            import os
            from ray_tpu.train.checkpoint import Checkpoint
            ckpt_dir = os.path.join(
                args.output_dir, f"checkpoint-{state.global_step}")
            if os.path.isdir(ckpt_dir):
                train_mod.report({"step": state.global_step,
                                  "hf_checkpoint": True},
                                 checkpoint=Checkpoint(ckpt_dir))

    if not any(type(cb).__name__ == "_RayTpuReportCallback"
               for cb in trainer.callback_handler.callbacks):
        trainer.add_callback(_RayTpuReportCallback())
    return trainer


class TransformersTrainer(DataParallelTrainer):
    """`trainer_init_per_worker(config) -> transformers.Trainer`; each
    rank builds its trainer inside the torch process group and trains."""

    _backend_config_cls = TorchConfig

    def __init__(self,
                 trainer_init_per_worker: Callable,
                 *,
                 trainer_init_config: Optional[Dict[str, Any]] = None,
                 torch_config: Optional[TorchConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        init_fn = trainer_init_per_worker

        def train_loop(config: Dict[str, Any]) -> None:
            import logging
            import os
            import ray_tpu.train as train_mod
            trainer = init_fn(config)
            prepare_trainer(trainer)
            ckpt = train_mod.get_checkpoint()
            resume = None
            if ckpt is not None:
                # only hand HF a dir it can actually resume from; a
                # non-HF checkpoint (user-reported dir, older run)
                # would raise inside trainer.train on EVERY restart,
                # turning a recoverable failure into a crash loop
                if os.path.exists(os.path.join(ckpt.path,
                                               "trainer_state.json")):
                    resume = ckpt.path
                else:
                    logging.getLogger(__name__).warning(
                        "checkpoint %s is not an HF trainer "
                        "checkpoint; training from scratch", ckpt.path)
            trainer.train(resume_from_checkpoint=resume)

        super().__init__(
            train_loop,
            train_loop_config=trainer_init_config,
            backend_config=torch_config or TorchConfig(),
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint)
