"""Worker-side training session.

reference parity: python/ray/train/_internal/session.py — _TrainSession
(:109), report (:653, via :393 _report_thread_runner_error plumbing),
get_checkpoint (:711), world_rank/world_size accessors. The user's
train_loop_per_worker runs on a daemon thread; `report(metrics,
checkpoint=...)` hands a result to the driver and blocks until consumed
(queue of size 1 — keeps workers paced with the driver like the
reference's result queue).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint


@dataclasses.dataclass
class TrainContext:
    """What a worker knows about itself (reference session accessors
    get_world_rank/get_world_size/get_local_rank/...)."""

    world_rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    local_world_size: int = 1
    node_rank: int = 0
    experiment_name: str = ""
    trial_dir: str = ""
    # Gang heartbeat channel id (train/heartbeat.py): set per gang
    # FORMATION by the backend executor — each elastic re-form gets a
    # fresh id so stale rows from a torn-down generation never shadow
    # the new gang. Empty = no heartbeat sidecar.
    gang_id: str = ""

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_local_world_size(self) -> int:
        return self.local_world_size

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_experiment_name(self) -> str:
        return self.experiment_name

    def get_trial_dir(self) -> str:
        return self.trial_dir


@dataclasses.dataclass
class TrainingResult:
    """One report() payload (reference _internal/session.py
    _TrainingResult)."""

    metrics: Dict[str, Any]
    checkpoint_dir: Optional[str] = None   # worker-local materialized dir
    rank: int = 0
    final: bool = False                     # loop returned
    error: Optional[BaseException] = None


class _TrainSession:
    """Runs the user loop on a thread; bridges report() to the driver."""

    def __init__(self, train_loop: Callable[..., Any],
                 config: Optional[Dict[str, Any]],
                 context: TrainContext,
                 starting_checkpoint: Optional[Checkpoint] = None,
                 dataset_shards: Optional[Dict[str, Any]] = None):
        self.context = context
        self.starting_checkpoint = starting_checkpoint
        self.dataset_shards = dataset_shards or {}
        self._results: "queue.Queue[TrainingResult]" = queue.Queue(maxsize=1)
        self._loop = train_loop
        self._config = config
        self._thread: Optional[threading.Thread] = None
        self._finished = False
        self._heartbeat = None

    # -- worker-loop side --------------------------------------------
    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        if self._heartbeat is not None:
            # the report round IS the supervisor's step unit: its
            # deadline is calibrated on report->report time
            self._heartbeat.note_step()
            self._heartbeat.set_phase("train")
        self._results.put(TrainingResult(
            metrics=dict(metrics),
            checkpoint_dir=checkpoint.path if checkpoint else None,
            rank=self.context.world_rank))

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.starting_checkpoint

    # -- actor side ---------------------------------------------------
    def start(self) -> None:
        if self.context.gang_id:
            # heartbeat sidecar: beats from its own thread + RpcClient
            # even while the loop thread sits inside a collective. A
            # SIGSTOP freezes it too — a STALE beat is the wedge signal.
            from ray_tpu.train.heartbeat import HeartbeatSender
            hb = HeartbeatSender(self.context.gang_id,
                                 self.context.world_rank)
            if hb.start():
                self._heartbeat = hb

        def runner():
            try:
                if self._config is not None:
                    self._loop(self._config)
                else:
                    self._loop()
                if self._heartbeat is not None:
                    self._heartbeat.set_phase("done")
                self._results.put(TrainingResult(
                    metrics={}, rank=self.context.world_rank, final=True))
            except BaseException as e:  # noqa: BLE001
                self._results.put(TrainingResult(
                    metrics={}, rank=self.context.world_rank, final=True,
                    error=e))

        self._thread = threading.Thread(
            target=runner, daemon=True,
            name=f"train-loop-rank{self.context.world_rank}")
        self._thread.start()

    def close(self) -> None:
        """Stop the heartbeat sidecar (gang teardown)."""
        if self._heartbeat is not None:
            self._heartbeat.stop()
            self._heartbeat = None

    def next_result(self, timeout: Optional[float] = None
                    ) -> Optional[TrainingResult]:
        """Block for the next report()/completion; None only on timeout."""
        if self._finished:
            return TrainingResult(metrics={},
                                  rank=self.context.world_rank, final=True)
        try:
            result = self._results.get(timeout=timeout)
        except queue.Empty:
            return None
        if result.final:
            self._finished = True
        return result


# Module-level session (one per worker process, like the reference's
# thread-local _session in _internal/session.py).
_session: Optional[_TrainSession] = None


def _set_session(s: Optional[_TrainSession]) -> None:
    global _session
    _session = s


def _get_session_or_raise() -> _TrainSession:
    if _session is None:
        raise RuntimeError(
            "No training session active: ray_tpu.train.report()/"
            "get_context() only work inside train_loop_per_worker")
    return _session


# -- public API (ray_tpu.train.{report,get_checkpoint,get_context}) ----
def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """reference train/_internal/session.py:653 ray.train.report."""
    _get_session_or_raise().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    """reference session.py:711 ray.train.get_checkpoint."""
    return _get_session_or_raise().get_checkpoint()


def get_context() -> TrainContext:
    """reference ray.train.get_context()."""
    return _get_session_or_raise().context


def get_dataset_shard(name: str = "train"):
    """This worker's shard of a dataset passed to the trainer (reference
    train/_internal/session.py:1017 get_dataset_shard). Returns a
    ray_tpu.data.DataIterator."""
    shards = _get_session_or_raise().dataset_shards
    if name not in shards:
        raise KeyError(
            f"no dataset shard named {name!r}: trainer was given "
            f"datasets={list(shards)}")
    return shards[name]
