"""BackendExecutor: drives the worker group through a training run.

reference parity: python/ray/train/_internal/backend_executor.py:65 —
start (:124, placement group at :200), _share_resource_ids (:258,286:
CUDA/neuron visibility sharing → here TPU chip visibility), rank mappings
(:358), start_training (:438), get_next_results (:552),
get_with_failure_handling (:640) and restart-on-failure (:701,712) bounded
by FailureConfig.max_failures (air/config.py:377).

Elastic mode (ScalingConfig.elastic_min_workers set): instead of the
fixed-size restart, worker death / node drain triggers a RECONFIGURATION
(TorchElastic re-rendezvous semantics): drain the old gang, fall back to
the latest durable checkpoint, re-form at whatever world size in
[elastic_min_workers, target] is schedulable within
elastic_reform_timeout_s, re-init the backend's process group
(jax.distributed) over the new mesh, re-split dataset shards, and resume
— each phase recorded as an `elastic.*` span with
ray_tpu_elastic_reconfigurations_total/_reconfig_seconds metrics and an
`elastic_stuck_reconfig` watchdog probe (train/elastic.py). Below-target
gangs keep their unscheduled bundles as replacement probes: the pending
placement-group demand is what autoscaler v2 feeds its scheduler, and
the probe turning ready (a replacement node joined) triggers the
scale-up reconfiguration back toward the target world size.
"""

from __future__ import annotations

import logging
import os
import time
import uuid
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional

from ray_tpu._private import goodput
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.session import TrainContext, TrainingResult
from ray_tpu.train.worker_group import WorkerGroup

logger = logging.getLogger(__name__)


class TrainingWorkerError(RuntimeError):
    """A worker's train loop raised; wraps the original error."""


class GangWedgedError(RuntimeError):
    """Rank(s) wedged mid-step: the step deadline expired with stale
    heartbeats (train/heartbeat.py). The wedged processes have already
    been hard-killed via their node managers by the time this raises —
    the caller routes it into the elastic re-form path with
    reason="wedge"."""


class BackendExecutor:
    def __init__(self, backend_config: BackendConfig,
                 scaling_config: ScalingConfig,
                 max_failures: int = 0):
        self._backend_config = backend_config
        self._backend: Backend = backend_config.backend_cls()
        self._scaling = scaling_config
        self._max_failures = max_failures
        self._num_failures = 0
        self.worker_group: Optional[WorkerGroup] = None
        self._contexts: List[TrainContext] = []
        # stashed so restarts can re-enter training transparently
        self._train_args: Optional[Dict[str, Any]] = None
        self._latest_checkpoint_dir: Optional[str] = None
        self._elastic = scaling_config.elastic
        self._tracker = None
        self._watch = None
        self._next_grow_poll = 0.0
        # collective-wedge watchdog (train/heartbeat.py): per-formation
        # heartbeat channel id + the per-step deadline calibrator.
        # Enforced only for elastic gangs — the recovery IS the elastic
        # re-form path — but heartbeats flow (and the gang_rank_wedged
        # probe watches them) for fixed gangs too.
        self._gang_uid: Optional[str] = None
        self._step_deadline = None
        if self._elastic:
            from ray_tpu.train.elastic import (MembershipWatch,
                                               ReconfigTracker)
            from ray_tpu.train.heartbeat import StepDeadline
            self._tracker = ReconfigTracker("train")
            self._watch = MembershipWatch()
            self._watch.subscribe()
            self._step_deadline = StepDeadline(
                scaling_config.step_deadline_s)

    # how long a RECONFIGURING gang waits for straggler bundles once
    # the minimum is met (TorchElastic proceed-with-survivors: recover
    # fast at the feasible size, grow when the replacement schedules);
    # the initial formation instead waits toward the full target
    RECONFIG_SETTLE_S = 2.0

    # fallback cadence for probing replacement capacity while degraded
    # when no pubsub capacity event arrived (pubsub can be unavailable
    # — MembershipWatch.subscribe is best-effort)
    GROW_POLL_PERIOD_S = 5.0

    # wedge supervisor: how often the elastic result wait wakes to
    # check membership/deadline state, and how often it refreshes the
    # gang heartbeat table from the GCS while a round is in flight
    # (also picks up the metrics_configure step-deadline override)
    WEDGE_POLL_S = 1.0
    WEDGE_HB_REFRESH_S = 2.0

    # ---- lifecycle --------------------------------------------------
    def start(self) -> None:
        self._form_group()
        self._mesh_init()

    def _form_group(self, settle_s: Optional[float] = None) -> None:
        """Create the worker gang + rank contexts (+ TPU visibility).
        Elastic gangs form at any size in [elastic_min_workers, target]
        bounded by elastic_reform_timeout_s; infeasible demand raises
        TrainingWorkerError naming what could not schedule."""
        target = self._scaling.elastic_target_workers if self._elastic \
            else self._scaling.num_workers
        kwargs: Dict[str, Any] = {}
        if self._elastic:
            kwargs["min_workers"] = self._scaling.elastic_min_workers
            kwargs["reform_timeout_s"] = \
                self._scaling.elastic_reform_timeout_s
            kwargs["reform_settle_s"] = settle_s
        gang_env = self._backend.gang_env(self._backend_config,
                                          num_workers=target)
        if gang_env:
            kwargs["runtime_env"] = gang_env
        try:
            self.worker_group = WorkerGroup(
                target,
                self._scaling._resources_per_worker_not_none,
                self._scaling.placement_strategy, **kwargs)
        except TimeoutError as e:
            raise TrainingWorkerError(
                f"gang formation infeasible: {e}") from e
        if self._elastic and len(self.worker_group) < target:
            logger.warning(
                "elastic gang formed below target: %d/%d workers "
                "(min=%d); unscheduled bundles kept as replacement "
                "probes", len(self.worker_group), target,
                self._scaling.elastic_min_workers)
        self._contexts = self._build_contexts(self.worker_group)
        # fresh heartbeat channel per FORMATION: rows from a torn-down
        # generation must never read as this gang's liveness
        self._gang_uid = f"train:{uuid.uuid4().hex[:8]}"
        for ctx in self._contexts:
            ctx.gang_id = self._gang_uid
        if self._scaling.num_tpus_per_worker:
            self._share_tpu_visibility(self.worker_group)
        if self._watch is not None:
            self._watch.watch_nodes(list(self.worker_group.node_ids))

    def _mesh_init(self) -> None:
        """Backend process-group setup (jax.distributed over the gang)."""
        self._backend.on_start(self.worker_group, self._backend_config)

    def _build_contexts(self, wg: WorkerGroup) -> List[TrainContext]:
        """World/local/node ranks from the sorted gang (reference
        backend_executor.py:358 _create_rank_world_size_mappings)."""
        node_to_workers: Dict[str, List[int]] = defaultdict(list)
        for rank, node_id in enumerate(wg.node_ids):
            node_to_workers[node_id].append(rank)
        node_rank = {nid: i for i, nid in enumerate(
            dict.fromkeys(wg.node_ids))}
        contexts = []
        for rank, node_id in enumerate(wg.node_ids):
            peers = node_to_workers[node_id]
            contexts.append(TrainContext(
                world_rank=rank,
                world_size=len(wg),
                local_rank=peers.index(rank),
                local_world_size=len(peers),
                node_rank=node_rank[node_id],
            ))
        return contexts

    def _share_tpu_visibility(self, wg: WorkerGroup) -> None:
        """Split the node's TPU chips among co-located workers
        (reference backend_executor.py:258 shares CUDA_VISIBLE_DEVICES;
        TPU env contract per _private/accelerators/tpu.py:157-196)."""
        from ray_tpu._private.accelerators.tpu import (
            TPU_CHIPS_PER_HOST_BOUNDS_ENV, TPU_HOST_BOUNDS_ENV,
            TPU_SINGLE_HOST_BOUNDS, TPU_VISIBLE_CHIPS_ENV)

        per_worker = int(self._scaling.num_tpus_per_worker)
        env_per_worker: List[Dict[str, str]] = []
        next_chip: Dict[str, int] = defaultdict(int)
        for ctx, node_id in zip(self._contexts, wg.node_ids):
            start = next_chip[node_id]
            chips = list(range(start, start + per_worker))
            next_chip[node_id] += per_worker
            env = {TPU_VISIBLE_CHIPS_ENV:
                   ",".join(str(c) for c in chips)}
            # sub-host slicing bounds (1/2/4-chip topologies)
            if per_worker == 1:
                env[TPU_CHIPS_PER_HOST_BOUNDS_ENV] = "1,1,1"
                env[TPU_HOST_BOUNDS_ENV] = TPU_SINGLE_HOST_BOUNDS
            elif per_worker == 2:
                env[TPU_CHIPS_PER_HOST_BOUNDS_ENV] = "1,2,1"
                env[TPU_HOST_BOUNDS_ENV] = TPU_SINGLE_HOST_BOUNDS
            env_per_worker.append(env)
        wg.setup_env(env_per_worker)

    # ---- training ---------------------------------------------------
    def start_training(self, train_loop: Callable,
                       config: Optional[Dict[str, Any]],
                       checkpoint_dir: Optional[str] = None,
                       experiment_name: str = "",
                       trial_dir: str = "",
                       datasets: Optional[Dict[str, Any]] = None) -> None:
        assert self.worker_group is not None, "call start() first"
        self._train_args = {
            "train_loop": train_loop, "config": config,
            "experiment_name": experiment_name, "trial_dir": trial_dir,
            "datasets": datasets,
        }
        self._latest_checkpoint_dir = checkpoint_dir
        if checkpoint_dir is not None:
            # resuming a prior run: session init re-reads model state
            # from the durable checkpoint on every rank
            with goodput.bucket("checkpoint_restore"):
                self._init_sessions(checkpoint_dir)
        else:
            self._init_sessions(checkpoint_dir)
        self._start_sessions()

    def _init_sessions(self, checkpoint_dir: Optional[str]) -> None:
        """Session setup on every rank: backend training hook, dataset
        shard split at the CURRENT world size, per-rank session init
        with the resume checkpoint (this is where an elastic re-form
        reshards: shards re-split over the new world, and every rank's
        loop reloads/reshards model+optimizer state from the durable
        checkpoint it is handed)."""
        assert self._train_args is not None
        self._backend.on_training_start(self.worker_group,
                                        self._backend_config)
        import ray_tpu
        # Disjoint per-rank dataset shards (reference backend_executor +
        # session.py:1017 get_dataset_shard contract).
        datasets = self._train_args.get("datasets")
        shards_per_rank: Optional[List[Dict[str, Any]]] = None
        if datasets:
            world = len(self.worker_group)
            shards_per_rank = [dict() for _ in range(world)]
            for name, ds in datasets.items():
                # equal=True: every rank must get a non-empty shard or an
                # SPMD loop doing per-batch collectives would deadlock.
                for rank, shard in enumerate(ds.split(world, equal=True)):
                    shards_per_rank[rank][name] = shard.iterator()
        refs = []
        for rank, w in enumerate(self.worker_group.workers):
            ctx = self._contexts[rank]
            ctx.experiment_name = self._train_args["experiment_name"]
            ctx.trial_dir = self._train_args["trial_dir"]
            refs.append(w.init_session.remote(
                self._train_args["train_loop"],
                self._train_args["config"], ctx, checkpoint_dir,
                shards_per_rank[rank] if shards_per_rank else None))
        ray_tpu.get(refs, timeout=120)

    def _start_sessions(self) -> None:
        import ray_tpu
        ray_tpu.get([w.start_training_session.remote()
                     for w in self.worker_group.workers], timeout=120)

    def get_next_results(self, timeout: float = 600.0
                         ) -> Optional[List[TrainingResult]]:
        """One result per worker, or None when all loops finished.

        Worker failures raise TrainingWorkerError after restart budget is
        exhausted; otherwise the group is restarted (elastic:
        reconfigured at the feasible world size) from the latest
        checkpoint and training resumes (reference
        backend_executor.py:552,640-712)."""
        import ray_tpu
        assert self.worker_group is not None
        while True:
            if self._elastic:
                lost = self._lost_gang_nodes()
                if lost:
                    logger.warning(
                        "elastic: gang node(s) %s declared dead; "
                        "reconfiguring", [n[:12] for n in lost])
                    self._handle_failure(TrainingWorkerError(
                        f"gang node(s) {[n[:12] for n in lost]} died"))
                    continue
                self._maybe_grow()
            try:
                # the round wait IS the training step from the driver's
                # vantage: the gang is stepping (goodput) until a span
                # inside re-attributes (compile charge, elastic window)
                with goodput.bucket(goodput.PRODUCTIVE):
                    refs = [w.next_result.remote(timeout=timeout)
                            for w in self.worker_group.workers]
                    if self._elastic:
                        # wedge-aware wait: poll so a rank hung INSIDE
                        # a collective (stale heartbeat + expired step
                        # deadline) is detected and hard-killed instead
                        # of blocking the whole gang for the full
                        # timeout
                        results = self._await_round(refs, timeout)
                    else:
                        # the get IS batched; the loop is the restart
                        # path
                        results = ray_tpu.get(  # graftlint: disable=RT002
                            refs, timeout=timeout + 60)
            except Exception as e:  # noqa: BLE001 - actor death etc.
                self._handle_failure(e)
                continue
            errors = [r.error for r in results
                      if r is not None and r.error is not None]
            if errors:
                self._handle_failure(errors[0])
                continue
            finals = [r is not None and r.final for r in results]
            if all(finals):
                return None
            if any(finals):
                # Uneven report() counts across ranks is a train-loop bug;
                # surface it instead of mixing empty final results into a
                # live round (reference backend_executor.py:581 raises
                # RuntimeError on partial completion).
                done = [i for i, f in enumerate(finals) if f]
                raise TrainingWorkerError(
                    f"workers {done} finished while others are still "
                    "reporting — all ranks must call report() the same "
                    "number of times")
            return [r for r in results if r is not None]

    # ---- collective-wedge supervisor (train/heartbeat.py) -----------
    def _await_round(self, refs: List[Any], timeout: float
                     ) -> List[Optional[TrainingResult]]:
        """Await one result round with the wedge trip armed.

        Short wait slices instead of one blocking get; between slices
        the supervisor refreshes the gang heartbeat table (which also
        carries the runtime step-deadline override) and, once the step
        deadline has expired, checks for stale ranks. The trip is
        two-factor by design: deadline expired AND >= 1 stale heartbeat.
        Every-rank-fresh-but-slow keeps waiting — auto-calibration plus
        the liveness factor is what keeps slow steps from false-
        tripping. On a trip the wedged pids are hard-killed via their
        node managers (a SIGSTOP'd rank answers no RPC) and
        GangWedgedError routes into the elastic re-form with
        reason="wedge". Round times feed the deadline calibrator."""
        import ray_tpu
        from ray_tpu.train import heartbeat as hb
        t0 = time.monotonic()
        hb_next = 0.0
        override: Optional[float] = None
        while True:
            ready, pending = ray_tpu.wait(
                refs, num_returns=len(refs), timeout=self.WEDGE_POLL_S)
            if not pending:
                results = ray_tpu.get(  # graftlint: disable=RT002
                    refs, timeout=60)
                self._step_deadline.observe(time.monotonic() - t0)
                return results
            now = time.monotonic()
            if now - t0 > timeout + 60:
                # mirror the blocking get's outer bound: workers are
                # paced by next_result(timeout) so a round this old is
                # a stuck gang even with fresh heartbeats
                raise TimeoutError(
                    f"no result round within {timeout + 60:.0f}s")
            if now < hb_next:
                continue
            hb_next = now + self.WEDGE_HB_REFRESH_S
            reply = self._query_heartbeats()
            if reply is None:
                continue
            if reply.get("step_deadline_override_s") is not None:
                override = reply["step_deadline_override_s"]
            deadline = self._step_deadline.current(override)
            if deadline is None or now - t0 < deadline:
                continue
            from ray_tpu._private.config import Config
            stale = hb.stale_ranks(reply,
                                   Config.watchdog_gang_heartbeat_s)
            if not stale:
                continue  # slow but every rank alive: keep waiting
            self._trip_wedge(reply, stale, deadline, now - t0)

    def _query_heartbeats(self) -> Optional[Dict[str, Any]]:
        if self._gang_uid is None:
            return None
        from ray_tpu.train import heartbeat as hb
        from ray_tpu.train.elastic import _core_worker_or_none
        cw = _core_worker_or_none()
        if cw is None:
            return None
        try:
            return hb.query_gang(cw._gcs.call, self._gang_uid)
        except Exception:  # noqa: BLE001 - GCS hiccup: retry next slice
            return None

    def _trip_wedge(self, reply: Dict[str, Any],
                    stale: List[Dict[str, Any]], deadline: float,
                    waited: float) -> None:
        from ray_tpu._private import spans
        from ray_tpu.train import heartbeat as hb
        cls = hb.classify_wedge(reply, stale)
        spans.instant(
            "elastic.wedge_detect", gang=self._gang_uid,
            classification=cls["kind"],
            ranks=",".join(str(r) for r in cls["ranks"]),
            nodes=",".join(n[:12] for n in cls["nodes"]),
            deadline_s=round(deadline, 3), waited_s=round(waited, 3))
        logger.error(
            "elastic: step deadline %.1fs expired after %.1fs with "
            "stale heartbeat(s) from rank(s) %s — %s; hard-killing "
            "wedged processes and re-forming",
            deadline, waited, cls["ranks"],
            "whole-node wedge, classifying as slice leave of %s"
            % [n[:12] for n in cls["nodes"]]
            if cls["kind"] == "slice_leave" else "isolated rank wedge")
        killed = hb.hard_kill_ranks(stale)
        raise GangWedgedError(
            f"rank(s) {cls['ranks']} wedged mid-step "
            f"({cls['kind']}): step deadline {deadline:.1f}s expired "
            f"after {waited:.1f}s with heartbeats "
            f"{[round(r['age_s'], 1) for r in stale]}s stale; "
            f"hard-killed ranks {killed} via their node managers")

    # ---- elastic reconfiguration ------------------------------------
    def _lost_gang_nodes(self) -> List[str]:
        """Nodes hosting gang members that the GCS declared dead (via
        the MembershipWatch "node" subscription). A slice preemption
        takes the host down with the workers — the gang must not wait
        for a worker RPC to fail (the driver<->worker channel can
        outlive the node's management plane)."""
        if self._watch is None or self.worker_group is None:
            return []
        lost = self._watch.take_lost_nodes()
        if not lost:
            return []
        gang_nodes = set(self.worker_group.node_ids)
        return [n for n in lost if n in gang_nodes]

    def _maybe_grow(self) -> None:
        """Scale-up trigger, checked at step boundaries: a replacement
        probe became schedulable (a node joined — autoscaler v2 supply
        or manual), so re-form toward the target world size. The
        capacity pubsub event triggers the probe poll immediately;
        otherwise poll at GROW_POLL_PERIOD_S — probe_ready() costs one
        GCS RPC per pending probe, too much for every step boundary of
        a long degraded run."""
        wg = self.worker_group
        if wg is None or wg.missing_workers() == 0:
            return
        event = self._watch.take_capacity_event() \
            if self._watch is not None else False
        now = time.monotonic()
        if not event and now < self._next_grow_poll:
            return
        self._next_grow_poll = now + self.GROW_POLL_PERIOD_S
        if wg.probe_ready():
            logger.info(
                "elastic: replacement capacity arrived; growing gang "
                "%d -> %d workers", len(wg), wg.target_workers)
            try:
                self._reconfigure("scale_up")
            except TrainingWorkerError:
                raise  # infeasible re-form: a clear terminal verdict
            except Exception as e:  # noqa: BLE001 - a kill can land
                # mid-grow (the gang is already drained at that point):
                # spend the restart budget like any other failure
                # instead of escaping fit() as a raw crash
                self._handle_failure(e)

    def _handle_failure(self, error: BaseException) -> None:
        # a kill can land DURING the recovery itself (chaos loves the
        # re-form window): recovery failures spend the same restart
        # budget instead of aborting the run on the first unlucky race
        while True:
            self._num_failures += 1
            if self._max_failures >= 0 and \
                    self._num_failures > self._max_failures:
                raise TrainingWorkerError(
                    f"training failed after {self._num_failures - 1} "
                    f"restart(s): {error!r}") from error
            logger.warning(
                "train worker failure %d/%s (%r); %s from latest "
                "checkpoint", self._num_failures,
                self._max_failures if self._max_failures >= 0 else "inf",
                error,
                "reconfiguring gang" if self._elastic
                else "restarting group")
            try:
                if self._elastic:
                    self._reconfigure(
                        "wedge" if isinstance(error, GangWedgedError)
                        else "worker_death")
                else:
                    self._restart()
                return
            except TrainingWorkerError:
                raise  # infeasible re-form: a clear terminal verdict
            except Exception as e:  # noqa: BLE001 - recovery raced a
                error = e           # new death; retry on budget

    def _reconfigure(self, reason: str) -> None:
        """One elastic reconfiguration: drain -> checkpoint -> reform ->
        reshard -> resume, span-recorded and metered (train/elastic.py).
        Raises TrainingWorkerError when the re-form is infeasible below
        elastic_min_workers within the deadline."""
        assert self._train_args is not None, "no training to reconfigure"
        rec = self._tracker.start(
            reason, world_size=len(self.worker_group)
            if self.worker_group is not None else 0)
        try:
            with rec.phase("drain"):
                self._teardown_group()
            with rec.phase("checkpoint") as attrs:
                ckpt = self._latest_checkpoint_dir
                if ckpt is not None and not os.path.isdir(ckpt):
                    logger.warning(
                        "elastic: latest checkpoint %s is gone; "
                        "resuming from scratch", ckpt)
                    ckpt = None
                attrs["checkpoint_dir"] = ckpt or ""
            with rec.phase("reform"):
                self._form_group(settle_s=self.RECONFIG_SETTLE_S)
            with rec.phase("reshard",
                           world_size=len(self.worker_group)):
                self._mesh_init()
                self._init_sessions(ckpt)
            with rec.phase("resume"):
                self._start_sessions()
            rec.finish(len(self.worker_group))
        except BaseException as e:
            rec.abort(e)
            raise

    def _restart(self) -> None:
        assert self._train_args is not None, "no training to restart"
        self._teardown_group()
        self.start()
        self.start_training(
            self._train_args["train_loop"], self._train_args["config"],
            checkpoint_dir=self._latest_checkpoint_dir,
            experiment_name=self._train_args["experiment_name"],
            trial_dir=self._train_args["trial_dir"],
            datasets=self._train_args.get("datasets"))

    def note_checkpoint(self, checkpoint_dir: str) -> None:
        """Driver tells the executor where the latest persisted checkpoint
        lives so restarts resume from it."""
        self._latest_checkpoint_dir = checkpoint_dir

    def _teardown_group(self) -> None:
        if self.worker_group is not None:
            try:
                self._backend.on_shutdown(self.worker_group,
                                          self._backend_config)
            except Exception:  # noqa: BLE001 - backend teardown is best-effort
                pass
            self.worker_group.shutdown()
            self.worker_group = None
        if self._gang_uid is not None:
            # drop the formation's heartbeat rows: a dead generation's
            # rows would export as wedged-forever gauge series
            from ray_tpu.train.elastic import _core_worker_or_none
            from ray_tpu.train.heartbeat import clear_gang
            cw = _core_worker_or_none()
            if cw is not None:
                clear_gang(cw._gcs.call, self._gang_uid)
            self._gang_uid = None

    def shutdown(self) -> None:
        self._teardown_group()
        if self._watch is not None:
            self._watch.unsubscribe()
        if self._tracker is not None:
            self._tracker.close()
