"""BackendExecutor: drives the worker group through a training run.

reference parity: python/ray/train/_internal/backend_executor.py:65 —
start (:124, placement group at :200), _share_resource_ids (:258,286:
CUDA/neuron visibility sharing → here TPU chip visibility), rank mappings
(:358), start_training (:438), get_next_results (:552),
get_with_failure_handling (:640) and restart-on-failure (:701,712) bounded
by FailureConfig.max_failures (air/config.py:377).
"""

from __future__ import annotations

import logging
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.session import TrainContext, TrainingResult
from ray_tpu.train.worker_group import WorkerGroup

logger = logging.getLogger(__name__)


class TrainingWorkerError(RuntimeError):
    """A worker's train loop raised; wraps the original error."""


class BackendExecutor:
    def __init__(self, backend_config: BackendConfig,
                 scaling_config: ScalingConfig,
                 max_failures: int = 0):
        self._backend_config = backend_config
        self._backend: Backend = backend_config.backend_cls()
        self._scaling = scaling_config
        self._max_failures = max_failures
        self._num_failures = 0
        self.worker_group: Optional[WorkerGroup] = None
        self._contexts: List[TrainContext] = []
        # stashed so restarts can re-enter training transparently
        self._train_args: Optional[Dict[str, Any]] = None
        self._latest_checkpoint_dir: Optional[str] = None

    # ---- lifecycle --------------------------------------------------
    def start(self) -> None:
        self.worker_group = WorkerGroup(
            self._scaling.num_workers,
            self._scaling._resources_per_worker_not_none,
            self._scaling.placement_strategy)
        self._contexts = self._build_contexts(self.worker_group)
        if self._scaling.num_tpus_per_worker:
            self._share_tpu_visibility(self.worker_group)
        self._backend.on_start(self.worker_group, self._backend_config)

    def _build_contexts(self, wg: WorkerGroup) -> List[TrainContext]:
        """World/local/node ranks from the sorted gang (reference
        backend_executor.py:358 _create_rank_world_size_mappings)."""
        node_to_workers: Dict[str, List[int]] = defaultdict(list)
        for rank, node_id in enumerate(wg.node_ids):
            node_to_workers[node_id].append(rank)
        node_rank = {nid: i for i, nid in enumerate(
            dict.fromkeys(wg.node_ids))}
        contexts = []
        for rank, node_id in enumerate(wg.node_ids):
            peers = node_to_workers[node_id]
            contexts.append(TrainContext(
                world_rank=rank,
                world_size=len(wg),
                local_rank=peers.index(rank),
                local_world_size=len(peers),
                node_rank=node_rank[node_id],
            ))
        return contexts

    def _share_tpu_visibility(self, wg: WorkerGroup) -> None:
        """Split the node's TPU chips among co-located workers
        (reference backend_executor.py:258 shares CUDA_VISIBLE_DEVICES;
        TPU env contract per _private/accelerators/tpu.py:157-196)."""
        from ray_tpu._private.accelerators.tpu import (
            TPU_CHIPS_PER_HOST_BOUNDS_ENV, TPU_HOST_BOUNDS_ENV,
            TPU_SINGLE_HOST_BOUNDS, TPU_VISIBLE_CHIPS_ENV)

        per_worker = int(self._scaling.num_tpus_per_worker)
        env_per_worker: List[Dict[str, str]] = []
        next_chip: Dict[str, int] = defaultdict(int)
        for ctx, node_id in zip(self._contexts, wg.node_ids):
            start = next_chip[node_id]
            chips = list(range(start, start + per_worker))
            next_chip[node_id] += per_worker
            env = {TPU_VISIBLE_CHIPS_ENV:
                   ",".join(str(c) for c in chips)}
            # sub-host slicing bounds (1/2/4-chip topologies)
            if per_worker == 1:
                env[TPU_CHIPS_PER_HOST_BOUNDS_ENV] = "1,1,1"
                env[TPU_HOST_BOUNDS_ENV] = TPU_SINGLE_HOST_BOUNDS
            elif per_worker == 2:
                env[TPU_CHIPS_PER_HOST_BOUNDS_ENV] = "1,2,1"
                env[TPU_HOST_BOUNDS_ENV] = TPU_SINGLE_HOST_BOUNDS
            env_per_worker.append(env)
        wg.setup_env(env_per_worker)

    # ---- training ---------------------------------------------------
    def start_training(self, train_loop: Callable,
                       config: Optional[Dict[str, Any]],
                       checkpoint_dir: Optional[str] = None,
                       experiment_name: str = "",
                       trial_dir: str = "",
                       datasets: Optional[Dict[str, Any]] = None) -> None:
        assert self.worker_group is not None, "call start() first"
        self._train_args = {
            "train_loop": train_loop, "config": config,
            "experiment_name": experiment_name, "trial_dir": trial_dir,
            "datasets": datasets,
        }
        self._latest_checkpoint_dir = checkpoint_dir
        self._backend.on_training_start(self.worker_group,
                                        self._backend_config)
        import ray_tpu
        # Disjoint per-rank dataset shards (reference backend_executor +
        # session.py:1017 get_dataset_shard contract).
        shards_per_rank: Optional[List[Dict[str, Any]]] = None
        if datasets:
            world = len(self.worker_group)
            shards_per_rank = [dict() for _ in range(world)]
            for name, ds in datasets.items():
                # equal=True: every rank must get a non-empty shard or an
                # SPMD loop doing per-batch collectives would deadlock.
                for rank, shard in enumerate(ds.split(world, equal=True)):
                    shards_per_rank[rank][name] = shard.iterator()
        refs = []
        for rank, w in enumerate(self.worker_group.workers):
            ctx = self._contexts[rank]
            ctx.experiment_name = experiment_name
            ctx.trial_dir = trial_dir
            refs.append(w.init_session.remote(
                train_loop, config, ctx, checkpoint_dir,
                shards_per_rank[rank] if shards_per_rank else None))
        ray_tpu.get(refs, timeout=120)
        ray_tpu.get([w.start_training_session.remote()
                     for w in self.worker_group.workers], timeout=120)

    def get_next_results(self, timeout: float = 600.0
                         ) -> Optional[List[TrainingResult]]:
        """One result per worker, or None when all loops finished.

        Worker failures raise TrainingWorkerError after restart budget is
        exhausted; otherwise the group is restarted from the latest
        checkpoint and training resumes (reference
        backend_executor.py:552,640-712)."""
        import ray_tpu
        assert self.worker_group is not None
        while True:
            try:
                # the get IS batched; the loop is the restart-retry path
                results = ray_tpu.get(  # graftlint: disable=RT002
                    [w.next_result.remote(timeout=timeout)
                     for w in self.worker_group.workers],
                    timeout=timeout + 60)
            except Exception as e:  # noqa: BLE001 - actor death etc.
                self._handle_failure(e)
                continue
            errors = [r.error for r in results
                      if r is not None and r.error is not None]
            if errors:
                self._handle_failure(errors[0])
                continue
            finals = [r is not None and r.final for r in results]
            if all(finals):
                return None
            if any(finals):
                # Uneven report() counts across ranks is a train-loop bug;
                # surface it instead of mixing empty final results into a
                # live round (reference backend_executor.py:581 raises
                # RuntimeError on partial completion).
                done = [i for i, f in enumerate(finals) if f]
                raise TrainingWorkerError(
                    f"workers {done} finished while others are still "
                    "reporting — all ranks must call report() the same "
                    "number of times")
            return [r for r in results if r is not None]

    def _handle_failure(self, error: BaseException) -> None:
        self._num_failures += 1
        if self._max_failures >= 0 and self._num_failures > self._max_failures:
            raise TrainingWorkerError(
                f"training failed after {self._num_failures - 1} "
                f"restart(s): {error!r}") from error
        logger.warning(
            "train worker failure %d/%s (%r); restarting group from "
            "latest checkpoint", self._num_failures,
            self._max_failures if self._max_failures >= 0 else "inf", error)
        self._restart()

    def _restart(self) -> None:
        assert self._train_args is not None, "no training to restart"
        self.shutdown()
        self.start()
        self.start_training(
            self._train_args["train_loop"], self._train_args["config"],
            checkpoint_dir=self._latest_checkpoint_dir,
            experiment_name=self._train_args["experiment_name"],
            trial_dir=self._train_args["trial_dir"],
            datasets=self._train_args.get("datasets"))

    def note_checkpoint(self, checkpoint_dir: str) -> None:
        """Driver tells the executor where the latest persisted checkpoint
        lives so restarts resume from it."""
        self._latest_checkpoint_dir = checkpoint_dir

    def shutdown(self) -> None:
        if self.worker_group is not None:
            try:
                self._backend.on_shutdown(self.worker_group,
                                          self._backend_config)
            except Exception:  # noqa: BLE001 - backend teardown is best-effort
                pass
            self.worker_group.shutdown()
            self.worker_group = None
