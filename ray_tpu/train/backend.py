"""Backend plugin contract.

reference parity: python/ray/train/backend.py:15,27 — BackendConfig /
Backend ABC with on_start / on_training_start / on_shutdown hooks run by
the BackendExecutor around worker-group lifecycle. The reference's
_TorchBackend does NCCL process-group setup here
(train/torch/config.py:148-200); the TPU build's JaxBackend instead wires
jax.distributed coordinator env + TPU slice visibility
(ray_tpu/train/jax_backend.py).
"""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING, Optional, Type

if TYPE_CHECKING:
    from ray_tpu.train.worker_group import WorkerGroup


@dataclasses.dataclass
class BackendConfig:
    """Base config; subclasses carry framework-specific knobs."""

    @property
    def backend_cls(self) -> Type["Backend"]:
        return Backend


class Backend:
    """Framework setup hooks (all optional)."""

    share_cuda_visible_devices: bool = False

    def gang_env(self, backend_config: BackendConfig,
                 num_workers: int = 1) -> Optional[dict]:
        """Per-formation runtime_env for the worker gang, or None.

        A backend whose process-group runtime can only initialize in a
        FRESH process (jax.distributed must run before any other jax
        use) returns a runtime_env with a unique key here: every gang
        formation then gets its own worker-pool bucket of brand-new
        processes, which is what makes elastic re-formation (tearing a
        gang down and re-forming at a new world size) safe to repeat.
        `num_workers` is the formation's target world size, so an
        auto-mode backend can decide before any worker exists."""
        return None

    def on_start(self, worker_group: "WorkerGroup",
                 backend_config: BackendConfig) -> None:
        pass

    def on_training_start(self, worker_group: "WorkerGroup",
                          backend_config: BackendConfig) -> None:
        pass

    def on_shutdown(self, worker_group: "WorkerGroup",
                    backend_config: BackendConfig) -> None:
        pass
