"""Backend plugin contract.

reference parity: python/ray/train/backend.py:15,27 — BackendConfig /
Backend ABC with on_start / on_training_start / on_shutdown hooks run by
the BackendExecutor around worker-group lifecycle. The reference's
_TorchBackend does NCCL process-group setup here
(train/torch/config.py:148-200); the TPU build's JaxBackend instead wires
jax.distributed coordinator env + TPU slice visibility
(ray_tpu/train/jax_backend.py).
"""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING, Type

if TYPE_CHECKING:
    from ray_tpu.train.worker_group import WorkerGroup


@dataclasses.dataclass
class BackendConfig:
    """Base config; subclasses carry framework-specific knobs."""

    @property
    def backend_cls(self) -> Type["Backend"]:
        return Backend


class Backend:
    """Framework setup hooks (all optional)."""

    share_cuda_visible_devices: bool = False

    def on_start(self, worker_group: "WorkerGroup",
                 backend_config: BackendConfig) -> None:
        pass

    def on_training_start(self, worker_group: "WorkerGroup",
                          backend_config: BackendConfig) -> None:
        pass

    def on_shutdown(self, worker_group: "WorkerGroup",
                    backend_config: BackendConfig) -> None:
        pass
