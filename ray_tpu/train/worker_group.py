"""Worker group: N train-worker actors gang-scheduled in a placement group.

reference parity: python/ray/train/_internal/worker_group.py:19,102,365 —
RayTrainWorker actor + WorkerGroup with node/accelerator-sorted stable
ranks; placement group creation mirrors BackendExecutor.start
(_internal/backend_executor.py:200).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.session import (TrainContext, TrainingResult,
                                   _set_session, _TrainSession)


class RayTrainWorker:
    """The per-rank actor (reference worker_group.py:19). Hosts the
    session; also a generic `_execute` escape hatch used by backends."""

    def __init__(self) -> None:
        self._session: Optional[_TrainSession] = None

    def apply(self, fn: Callable, *args: Any, **kwargs: Any) -> Any:
        return fn(*args, **kwargs)

    def setup_env(self, env: Dict[str, str]) -> None:
        os.environ.update(env)

    def node_info(self) -> Tuple[str, int]:
        ctx = ray_tpu.get_runtime_context()
        return ctx.get_node_id(), os.getpid()

    def init_session(self, train_loop: Callable, config: Optional[Dict],
                      context: TrainContext,
                      checkpoint_dir: Optional[str],
                      dataset_shards: Optional[Dict] = None) -> None:
        ckpt = Checkpoint(checkpoint_dir) if checkpoint_dir else None
        self._session = _TrainSession(train_loop, config, context, ckpt,
                                      dataset_shards=dataset_shards)
        _set_session(self._session)

    def start_training_session(self) -> None:
        assert self._session is not None
        self._session.start()

    def next_result(self, timeout: Optional[float] = None):
        assert self._session is not None
        return self._session.next_result(timeout=timeout)

    def shutdown_session(self) -> None:
        self._session = None
        _set_session(None)


class WorkerGroup:
    """Creates/holds the actor gang (reference worker_group.py:102)."""

    def __init__(self, num_workers: int,
                 resources_per_worker: Dict[str, float],
                 placement_strategy: str = "PACK"):
        from ray_tpu.util import (PlacementGroupSchedulingStrategy,
                                  placement_group)

        self.num_workers = num_workers
        self._pg = placement_group(
            [dict(resources_per_worker) for _ in range(num_workers)],
            strategy=placement_strategy)
        if not self._pg.wait(120):
            from ray_tpu.util import remove_placement_group
            remove_placement_group(self._pg)
            raise TimeoutError(
                f"placement group for {num_workers} x "
                f"{resources_per_worker} not schedulable within 120s")

        cls = ray_tpu.remote(RayTrainWorker)
        self.workers = [
            cls.options(
                num_cpus=0,
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=self._pg,
                    placement_group_bundle_index=i)).remote()
            for i in range(num_workers)
        ]
        # Stable rank order: sort by node id then pid (reference
        # worker_group.py:365 sorts by node + GPU ids for deterministic
        # rank assignment).
        infos = ray_tpu.get(
            [w.node_info.remote() for w in self.workers], timeout=120)
        order = sorted(range(num_workers),
                       key=lambda i: (infos[i][0], infos[i][1]))
        self.workers = [self.workers[i] for i in order]
        self.node_ids = [infos[i][0] for i in order]

    @property
    def placement_group(self):
        return self._pg

    def execute(self, fn: Callable, *args: Any, **kwargs: Any) -> List[Any]:
        """Run fn on every worker, gather results (reference
        WorkerGroup.execute)."""
        return ray_tpu.get(
            [w.apply.remote(fn, *args, **kwargs) for w in self.workers],
            timeout=300)

    def execute_single(self, rank: int, fn: Callable, *args: Any,
                       **kwargs: Any) -> Any:
        return ray_tpu.get(
            self.workers[rank].apply.remote(fn, *args, **kwargs),
            timeout=300)

    def setup_env(self, env_per_worker: List[Dict[str, str]]) -> None:
        ray_tpu.get([w.setup_env.remote(env)
                     for w, env in zip(self.workers, env_per_worker)],
                    timeout=120)

    def shutdown(self) -> None:
        from ray_tpu.util import remove_placement_group
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001 - worker already dead
                pass
        try:
            remove_placement_group(self._pg)
        except Exception:  # noqa: BLE001 - group already removed
            pass
        self.workers = []

    def __len__(self) -> int:
        return len(self.workers)
