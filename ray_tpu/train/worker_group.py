"""Worker group: N train-worker actors gang-scheduled in a placement group.

reference parity: python/ray/train/_internal/worker_group.py:19,102,365 —
RayTrainWorker actor + WorkerGroup with node/accelerator-sorted stable
ranks; placement group creation mirrors BackendExecutor.start
(_internal/backend_executor.py:200).

Two formation modes:

- FIXED (min_workers=None): one num_workers-bundle placement group,
  all-or-nothing — the classic gang.
- ELASTIC (min_workers set): one single-bundle placement group PER
  worker, polled against a reform deadline. Formation proceeds with
  every bundle that became schedulable in time as long as that is
  >= min_workers; still-pending groups are KEPT as replacement probes
  (`probe_ready()` turning true = capacity for a bigger world arrived —
  the grow trigger for the elastic reconfiguration loop in
  backend_executor.py). An unschedulable probe also shows up as PENDING
  placement-group demand, which autoscaler v2's ClusterStatusReader
  feeds to the scheduler — the probe is simultaneously the demand
  signal that makes a replacement node appear and the sensor that
  notices it arrived.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.session import (TrainContext, TrainingResult,
                                   _set_session, _TrainSession)

logger = logging.getLogger(__name__)


class RayTrainWorker:
    """The per-rank actor (reference worker_group.py:19). Hosts the
    session; also a generic `_execute` escape hatch used by backends."""

    def __init__(self) -> None:
        self._session: Optional[_TrainSession] = None

    def apply(self, fn: Callable, *args: Any, **kwargs: Any) -> Any:
        return fn(*args, **kwargs)

    def setup_env(self, env: Dict[str, str]) -> None:
        os.environ.update(env)

    def node_info(self) -> Tuple[str, int]:
        ctx = ray_tpu.get_runtime_context()
        return ctx.get_node_id(), os.getpid()

    def init_session(self, train_loop: Callable, config: Optional[Dict],
                      context: TrainContext,
                      checkpoint_dir: Optional[str],
                      dataset_shards: Optional[Dict] = None) -> None:
        ckpt = Checkpoint(checkpoint_dir) if checkpoint_dir else None
        self._session = _TrainSession(train_loop, config, context, ckpt,
                                      dataset_shards=dataset_shards)
        _set_session(self._session)

    def start_training_session(self) -> None:
        assert self._session is not None
        self._session.start()

    def next_result(self, timeout: Optional[float] = None):
        assert self._session is not None
        return self._session.next_result(timeout=timeout)

    def shutdown_session(self) -> None:
        if self._session is not None:
            self._session.close()  # stop the heartbeat sidecar
        self._session = None
        _set_session(None)


class WorkerGroup:
    """Creates/holds the actor gang (reference worker_group.py:102)."""

    def __init__(self, num_workers: int,
                 resources_per_worker: Dict[str, float],
                 placement_strategy: str = "PACK", *,
                 min_workers: Optional[int] = None,
                 reform_timeout_s: Optional[float] = None,
                 reform_settle_s: Optional[float] = None,
                 runtime_env: Optional[Dict[str, Any]] = None):
        from ray_tpu.util import (PlacementGroupSchedulingStrategy,
                                  placement_group)

        self.target_workers = num_workers
        self.elastic = min_workers is not None
        self._resources = dict(resources_per_worker)
        self._runtime_env = runtime_env
        self.pending_pgs: List[Any] = []
        self._pgs: List[Any] = []

        if min_workers is None:
            # fixed gang: one all-or-nothing placement group
            pg = placement_group(
                [dict(resources_per_worker) for _ in range(num_workers)],
                strategy=placement_strategy)
            if not pg.wait(120):
                from ray_tpu.util import remove_placement_group
                remove_placement_group(pg)
                raise TimeoutError(
                    f"placement group for {num_workers} x "
                    f"{resources_per_worker} not schedulable within 120s")
            self._pg = pg
            self._pgs = [pg]
            bundle_slots = [(pg, i) for i in range(num_workers)]
        else:
            # elastic gang: one bundle per worker, bounded by the reform
            # deadline; proceed with >= min_workers ready bundles.
            # reform_settle_s (TorchElastic proceed-with-survivors
            # semantics, used by reconfigurations): once the minimum is
            # met, wait only this long past the LAST bundle that became
            # ready before going — stragglers stay behind as
            # replacement probes and the gang grows when they schedule.
            # None (initial formation) waits toward the full target
            # until the deadline.
            if placement_strategy != "PACK":
                # per-worker single-bundle groups cannot express
                # cross-worker (anti-)affinity — a SPREAD gang would
                # silently lose its blast-radius guarantee
                logger.warning(
                    "elastic formation ignores placement_strategy=%s: "
                    "workers form independent single-bundle placement "
                    "groups with no cross-worker affinity",
                    placement_strategy)
            deadline = time.monotonic() + (reform_timeout_s or 60.0)
            pgs = [placement_group([dict(resources_per_worker)],
                                   strategy="PACK")
                   for _ in range(num_workers)]
            ready: List[Any] = []
            pending: List[Any] = list(pgs)
            last_progress = time.monotonic()
            while pending and time.monotonic() < deadline:
                still = []
                for pg in pending:
                    if pg.is_ready():
                        ready.append(pg)
                        last_progress = time.monotonic()
                    else:
                        still.append(pg)
                pending = still
                if pending and reform_settle_s is not None and \
                        len(ready) >= min_workers and \
                        time.monotonic() - last_progress >= \
                        reform_settle_s:
                    break
                if pending:
                    time.sleep(0.1)
            if len(ready) < min_workers:
                from ray_tpu.util import remove_placement_group
                for pg in pgs:
                    try:
                        remove_placement_group(pg)
                    except Exception:  # noqa: BLE001 - already gone
                        pass
                raise TimeoutError(
                    f"only {len(ready)}/{num_workers} worker bundles of "
                    f"{resources_per_worker} schedulable within "
                    f"{reform_timeout_s or 60.0:.0f}s "
                    f"(elastic_min_workers={min_workers})")
            self._pg = ready[0]
            self._pgs = list(ready)
            self.pending_pgs = pending
            bundle_slots = [(pg, 0) for pg in ready]

        self.num_workers = len(bundle_slots)
        cls = ray_tpu.remote(RayTrainWorker)
        opts: Dict[str, Any] = {"num_cpus": 0}
        if runtime_env:
            opts["runtime_env"] = runtime_env
        self.workers = []
        try:
            self.workers = [
                cls.options(
                    scheduling_strategy=PlacementGroupSchedulingStrategy(
                        placement_group=pg,
                        placement_group_bundle_index=idx),
                    **opts).remote()
                for pg, idx in bundle_slots
            ]
            # Stable rank order: sort by node id then pid (reference
            # worker_group.py:365 sorts by node + GPU ids for
            # deterministic rank assignment).
            infos = ray_tpu.get(
                [w.node_info.remote() for w in self.workers],
                timeout=120)
        except BaseException:
            # a failed formation must release everything it claimed
            # (committed PGs, pending probes, spawned actors): the
            # caller holds no reference yet (__init__ raised), so a
            # leak keeps CPUs reserved and an elastic retry loop
            # compounds it until the cluster reads infeasible
            self.shutdown()
            raise
        order = sorted(range(self.num_workers),
                       key=lambda i: (infos[i][0], infos[i][1]))
        self.workers = [self.workers[i] for i in order]
        self.node_ids = [infos[i][0] for i in order]

    @property
    def placement_group(self):
        return self._pg

    # ---- elastic probes ---------------------------------------------
    def probe_ready(self) -> bool:
        """True when ANY kept replacement probe became schedulable —
        capacity for a larger world arrived. INFEASIBLE probes (the
        GCS gives up on a PENDING group after its scheduling deadline)
        are re-armed so a replacement arriving later still registers."""
        from ray_tpu.util import placement_group, remove_placement_group
        ready = False
        rearmed: List[Any] = []
        for pg in self.pending_pgs:
            if pg.is_ready():
                ready = True
                rearmed.append(pg)
                continue
            info = None
            try:
                info = pg._info()
            except Exception:  # noqa: BLE001 - GCS hiccup; keep probing
                pass
            if info is not None and info.state in ("INFEASIBLE",
                                                   "REMOVED"):
                try:
                    remove_placement_group(pg)
                except Exception:  # noqa: BLE001 - already gone
                    pass
                rearmed.append(placement_group([dict(self._resources)],
                                               strategy="PACK"))
            else:
                rearmed.append(pg)
        self.pending_pgs = rearmed
        return ready

    def missing_workers(self) -> int:
        return max(0, self.target_workers - len(self.workers))

    # ---- execution --------------------------------------------------
    def execute(self, fn: Callable, *args: Any, **kwargs: Any) -> List[Any]:
        """Run fn on every worker, gather results (reference
        WorkerGroup.execute)."""
        return ray_tpu.get(
            [w.apply.remote(fn, *args, **kwargs) for w in self.workers],
            timeout=300)

    def execute_single(self, rank: int, fn: Callable, *args: Any,
                       **kwargs: Any) -> Any:
        return ray_tpu.get(
            self.workers[rank].apply.remote(fn, *args, **kwargs),
            timeout=300)

    def setup_env(self, env_per_worker: List[Dict[str, str]]) -> None:
        ray_tpu.get([w.setup_env.remote(env)
                     for w, env in zip(self.workers, env_per_worker)],
                    timeout=120)

    def shutdown(self) -> None:
        from ray_tpu.util import remove_placement_group
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001 - worker already dead
                pass
        for pg in list(self._pgs) + list(self.pending_pgs):
            try:
                remove_placement_group(pg)
            except Exception:  # noqa: BLE001 - group already removed
                pass
        self._pgs = []
        self.pending_pgs = []
        self.workers = []

    def __len__(self) -> int:
        return len(self.workers)
