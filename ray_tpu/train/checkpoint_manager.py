"""Checkpoint bookkeeping: persist, rank, prune.

reference parity: python/ray/train/_internal/checkpoint_manager.py:43
(_CheckpointManager) honoring CheckpointConfig (air/config.py:428 —
num_to_keep, checkpoint_score_attribute/order).

Persistence is ATOMIC (tmp dir + per-file fsync + rename, directory
fsync'd) and the LATEST pointer file is updated LAST, also via
tmp+fsync+rename: a crash or chaos kill at ANY instant during a save
leaves either the previous pointer naming a complete checkpoint or the
new pointer naming the new complete checkpoint — never a torn resume
target. Unreferenced `.tmp-*` debris from an interrupted copy is
ignored by readers and swept on the next persist.
"""

from __future__ import annotations

import os
import shutil
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import CheckpointConfig

LATEST_POINTER = "LATEST"


@dataclass
class _TrackedCheckpoint:
    checkpoint: Checkpoint
    metrics: Dict[str, Any] = field(default_factory=dict)
    index: int = 0
    time: float = field(default_factory=time.time)


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _copy_fsync(src: str, dest: str) -> None:
    """copytree whose every file is flushed to disk before the caller
    renames the tree into place — the rename must never publish a
    directory whose file contents are still only in the page cache."""
    os.makedirs(dest, exist_ok=True)
    for root, dirs, files in os.walk(src):
        rel = os.path.relpath(root, src)
        out_root = dest if rel == "." else os.path.join(dest, rel)
        for d in dirs:
            os.makedirs(os.path.join(out_root, d), exist_ok=True)
        for name in files:
            out_path = os.path.join(out_root, name)
            with open(os.path.join(root, name), "rb") as fin, \
                    open(out_path, "wb") as fout:
                shutil.copyfileobj(fin, fout)
                fout.flush()
                os.fsync(fout.fileno())
        _fsync_dir(out_root)


def read_latest_pointer(run_dir: str) -> Optional[str]:
    """The path the LATEST pointer names, or None. Only ever names a
    fully-persisted checkpoint (the pointer is written after the data
    rename lands)."""
    p = os.path.join(run_dir, LATEST_POINTER)
    try:
        with open(p) as f:
            name = f.read().strip()
    except OSError:
        return None
    path = os.path.join(run_dir, name)
    return path if name and os.path.isdir(path) else None


def latest_checkpoint_path(run_dir: str) -> Optional[str]:
    """Resolve the resume target under a run dir: the LATEST pointer
    when present, else the newest complete checkpoint_* dir (pre-pointer
    runs). `.tmp-*` debris from interrupted persists never qualifies."""
    p = read_latest_pointer(run_dir)
    if p is not None:
        return p
    ckpts = sorted(
        d for d in os.listdir(run_dir)
        if d.startswith("checkpoint_")
        and os.path.isdir(os.path.join(run_dir, d)))
    return os.path.join(run_dir, ckpts[-1]) if ckpts else None


class CheckpointManager:
    def __init__(self, run_dir: str,
                 config: Optional[CheckpointConfig] = None):
        self.run_dir = run_dir
        self.config = config or CheckpointConfig()
        self._checkpoints: List[_TrackedCheckpoint] = []
        os.makedirs(run_dir, exist_ok=True)
        # Resume numbering past any checkpoints a prior run left in this
        # run dir: a fresh manager starting at 0 would target an existing
        # checkpoint_000001 and os.rename into a non-empty dir fails.
        self._counter = 0
        for d in os.listdir(run_dir):
            if d.startswith("checkpoint_"):
                try:
                    self._counter = max(self._counter,
                                        int(d.rsplit("_", 1)[-1]))
                except ValueError:
                    pass

    @property
    def latest(self) -> Optional[Checkpoint]:
        return self._checkpoints[-1].checkpoint if self._checkpoints else None

    @property
    def best(self) -> Optional[Checkpoint]:
        ranked = self._ranked()
        return ranked[0].checkpoint if ranked else None

    def list(self) -> List[Checkpoint]:
        return [t.checkpoint for t in self._checkpoints]

    def register(self, worker_dir: str,
                 metrics: Dict[str, Any]) -> Checkpoint:
        """Persist a worker-reported checkpoint dir into the run dir:
        copy+fsync into a tmp dir, rename into place, THEN advance the
        LATEST pointer — a kill mid-save can never leave a torn dir as
        the resume target."""
        from ray_tpu._private import goodput
        with goodput.bucket("checkpoint_save"):
            return self._register_impl(worker_dir, metrics)

    def _register_impl(self, worker_dir: str,
                       metrics: Dict[str, Any]) -> Checkpoint:
        self._sweep_tmp()
        self._counter += 1
        name = f"checkpoint_{self._counter:06d}"
        dest = os.path.join(self.run_dir, name)
        if os.path.abspath(worker_dir) != dest:
            tmp = os.path.join(self.run_dir,
                               f".tmp-{name}-{uuid.uuid4().hex[:8]}")
            try:
                _copy_fsync(worker_dir, tmp)
                os.rename(tmp, dest)
            except BaseException:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
            _fsync_dir(self.run_dir)
        self._write_latest_pointer(name)
        ckpt = Checkpoint(dest)
        self._checkpoints.append(_TrackedCheckpoint(
            checkpoint=ckpt, metrics=dict(metrics), index=self._counter))
        self._prune()
        return ckpt

    def _write_latest_pointer(self, name: str) -> None:
        """Atomic pointer update, strictly AFTER the checkpoint data
        rename: readers either see the previous pointer (previous valid
        checkpoint) or the new one (new valid checkpoint)."""
        final = os.path.join(self.run_dir, LATEST_POINTER)
        tmp = f"{final}.tmp-{uuid.uuid4().hex[:8]}"
        with open(tmp, "w") as f:
            f.write(name)
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, final)
        _fsync_dir(self.run_dir)

    def _sweep_tmp(self) -> None:
        """Clear debris a previous interrupted persist left behind
        (never referenced by the pointer, never ranked)."""
        for d in os.listdir(self.run_dir):
            if d.startswith(".tmp-") or (d.startswith(LATEST_POINTER)
                                         and d != LATEST_POINTER):
                p = os.path.join(self.run_dir, d)
                if os.path.isdir(p):
                    shutil.rmtree(p, ignore_errors=True)
                else:
                    try:
                        os.unlink(p)
                    except OSError:
                        pass

    def _ranked(self) -> List[_TrackedCheckpoint]:
        attr = self.config.checkpoint_score_attribute
        if attr is None:
            return list(reversed(self._checkpoints))  # newest first

        def score(t: _TrackedCheckpoint):
            return t.metrics.get(attr, float("-inf"))

        return sorted(self._checkpoints, key=score,
                      reverse=self.config.checkpoint_score_order == "max")

    def _prune(self) -> None:
        keep = self.config.num_to_keep
        if keep is None or len(self._checkpoints) <= keep:
            return
        ranked = self._ranked()
        doomed = ranked[keep:]
        # never delete the most recent checkpoint: restarts resume from it
        latest = self._checkpoints[-1]
        for t in doomed:
            if t is latest:
                continue
            self._checkpoints.remove(t)
            shutil.rmtree(t.checkpoint.path, ignore_errors=True)
