"""Checkpoint bookkeeping: persist, rank, prune.

reference parity: python/ray/train/_internal/checkpoint_manager.py:43
(_CheckpointManager) honoring CheckpointConfig (air/config.py:428 —
num_to_keep, checkpoint_score_attribute/order).
"""

from __future__ import annotations

import os
import shutil
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import CheckpointConfig


@dataclass
class _TrackedCheckpoint:
    checkpoint: Checkpoint
    metrics: Dict[str, Any] = field(default_factory=dict)
    index: int = 0
    time: float = field(default_factory=time.time)


class CheckpointManager:
    def __init__(self, run_dir: str,
                 config: Optional[CheckpointConfig] = None):
        self.run_dir = run_dir
        self.config = config or CheckpointConfig()
        self._checkpoints: List[_TrackedCheckpoint] = []
        self._counter = 0
        os.makedirs(run_dir, exist_ok=True)

    @property
    def latest(self) -> Optional[Checkpoint]:
        return self._checkpoints[-1].checkpoint if self._checkpoints else None

    @property
    def best(self) -> Optional[Checkpoint]:
        ranked = self._ranked()
        return ranked[0].checkpoint if ranked else None

    def list(self) -> List[Checkpoint]:
        return [t.checkpoint for t in self._checkpoints]

    def register(self, worker_dir: str,
                 metrics: Dict[str, Any]) -> Checkpoint:
        """Persist a worker-reported checkpoint dir into the run dir."""
        self._counter += 1
        dest = os.path.join(self.run_dir,
                            f"checkpoint_{self._counter:06d}")
        if os.path.abspath(worker_dir) != dest:
            shutil.copytree(worker_dir, dest, dirs_exist_ok=True)
        ckpt = Checkpoint(dest)
        self._checkpoints.append(_TrackedCheckpoint(
            checkpoint=ckpt, metrics=dict(metrics), index=self._counter))
        self._prune()
        return ckpt

    def _ranked(self) -> List[_TrackedCheckpoint]:
        attr = self.config.checkpoint_score_attribute
        if attr is None:
            return list(reversed(self._checkpoints))  # newest first

        def score(t: _TrackedCheckpoint):
            return t.metrics.get(attr, float("-inf"))

        return sorted(self._checkpoints, key=score,
                      reverse=self.config.checkpoint_score_order == "max")

    def _prune(self) -> None:
        keep = self.config.num_to_keep
        if keep is None or len(self._checkpoints) <= keep:
            return
        ranked = self._ranked()
        doomed = ranked[keep:]
        # never delete the most recent checkpoint: restarts resume from it
        latest = self._checkpoints[-1]
        for t in doomed:
            if t is latest:
                continue
            self._checkpoints.remove(t)
            shutil.rmtree(t.checkpoint.path, ignore_errors=True)
