"""SklearnTrainer: scikit-learn estimator fitting as a train run.

reference parity: python/ray/train/sklearn/sklearn_trainer.py — fits a
(non-distributed) sklearn estimator on one training actor, optionally
cross-validates, reports metrics and persists the fitted estimator as
the run checkpoint. Parallelism comes from the estimator's own n_jobs
(the reference registers a joblib-over-actors backend; here the single
fitting actor keeps its requested CPUs).
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Any, Dict, Optional

from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.data_parallel_trainer import Result


class SklearnTrainer:
    def __init__(self, *, estimator: Any,
                 datasets: Dict[str, Any],
                 label_column: str,
                 params: Optional[Dict[str, Any]] = None,
                 scoring: Optional[str] = None,
                 cv: Optional[int] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None):
        if "train" not in datasets:
            raise ValueError("datasets must include a 'train' entry")
        self.estimator = estimator
        self.datasets = datasets
        self.label_column = label_column
        self.params = dict(params or {})
        self.scoring = scoring
        self.cv = cv
        self.scaling_config = scaling_config or ScalingConfig(
            trainer_resources={"CPU": 1})
        self.run_config = run_config or RunConfig()

    def fit(self) -> Result:
        import ray_tpu

        run_name = self.run_config.name or \
            f"SklearnTrainer_{time.strftime('%Y%m%d_%H%M%S')}"
        run_dir = os.path.join(self.run_config.storage_path, run_name)
        os.makedirs(run_dir, exist_ok=True)

        def _fit(estimator_blob: bytes, datasets: Dict[str, Any],
                 label: str, params: Dict[str, Any],
                 scoring: Optional[str], cv: Optional[int],
                 run_dir: str) -> Dict[str, Any]:
            import numpy as np
            import pickle as _p
            est = _p.loads(estimator_blob)
            if params:
                est.set_params(**params)

            def split(block):
                y = np.asarray(block[label])
                feats = [np.asarray(v) for k, v in sorted(block.items())
                         if k != label]
                X = np.column_stack(feats)
                return X, y

            Xtr, ytr = split(datasets["train"])
            metrics: Dict[str, Any] = {}
            if cv:
                from sklearn.model_selection import cross_val_score
                scores = cross_val_score(est, Xtr, ytr, cv=cv,
                                         scoring=scoring)
                metrics["cv_scores"] = [float(s) for s in scores]
                metrics["cv_score_mean"] = float(np.mean(scores))
            t0 = time.perf_counter()
            est.fit(Xtr, ytr)
            metrics["fit_time"] = time.perf_counter() - t0
            metrics["train_score"] = float(est.score(Xtr, ytr))
            for name, block in datasets.items():
                if name == "train":
                    continue
                Xv, yv = split(block)
                metrics[f"{name}_score"] = float(est.score(Xv, yv))
            ckpt_dir = os.path.join(run_dir, "_worker_staging")
            os.makedirs(ckpt_dir, exist_ok=True)
            with open(os.path.join(ckpt_dir, "estimator.pkl"),
                      "wb") as f:
                _p.dump(est, f)
            metrics["checkpoint_dir"] = ckpt_dir
            return metrics

        cpus = (self.scaling_config.trainer_resources or
                {"CPU": 1}).get("CPU", 1)
        fit_remote = ray_tpu.remote(_fit).options(num_cpus=cpus)
        try:
            metrics = ray_tpu.get(fit_remote.remote(
                pickle.dumps(self.estimator), self.datasets,
                self.label_column, self.params, self.scoring, self.cv,
                run_dir), timeout=3600)
        except Exception as e:  # noqa: BLE001 — same contract as the
            # other trainers: errors surface on Result.error, not raise
            return Result(metrics={}, checkpoint=None, error=e,
                          path=run_dir)
        ckpt_dir = metrics.pop("checkpoint_dir")
        # register through the shared manager so
        # RunConfig.checkpoint_config (num_to_keep, score attr) applies
        from ray_tpu.train.checkpoint_manager import CheckpointManager
        mgr = CheckpointManager(run_dir,
                                self.run_config.checkpoint_config)
        ckpt = mgr.register(ckpt_dir, metrics)
        import shutil
        shutil.rmtree(ckpt_dir, ignore_errors=True)  # staged copy
        return Result(metrics=metrics, checkpoint=ckpt,
                      error=None, path=run_dir,
                      metrics_history=[dict(metrics)],
                      _best_checkpoints=mgr.list())
