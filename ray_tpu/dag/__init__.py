"""ray_tpu.dag: lazy task graphs (DAGNode API).

reference parity: python/ray/dag — DAGNode (dag_node.py:23),
FunctionNode, ClassNode/ClassMethodNode, InputNode: `.bind()` builds the
graph lazily; `.execute()` walks it, submitting each node as a task (or
actor call) with upstream results passed as ObjectRefs — used by Serve
app graphs and Workflow.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

_node_counter = [0]
_counter_lock = threading.Lock()


def _next_id() -> int:
    with _counter_lock:
        _node_counter[0] += 1
        return _node_counter[0]


class DAGNode:
    """Base graph node. Subclasses define _execute_impl."""

    def __init__(self, args: tuple, kwargs: Dict[str, Any]):
        self._bound_args = args
        self._bound_kwargs = kwargs
        self._id = _next_id()

    # -- traversal -----------------------------------------------------

    def _children(self) -> List["DAGNode"]:
        out = [a for a in self._bound_args if isinstance(a, DAGNode)]
        out += [v for v in self._bound_kwargs.values()
                if isinstance(v, DAGNode)]
        return out

    def _resolve_args(self, memo: Dict[int, Any],
                      dag_input: Any) -> Tuple[tuple, Dict[str, Any]]:
        def res(x: Any) -> Any:
            if isinstance(x, DAGNode):
                return x._execute_memo(memo, dag_input)
            return x
        return (tuple(res(a) for a in self._bound_args),
                {k: res(v) for k, v in self._bound_kwargs.items()})

    def _execute_memo(self, memo: Dict[int, Any], dag_input: Any) -> Any:
        if self._id not in memo:
            memo[self._id] = self._execute_impl(memo, dag_input)
        return memo[self._id]

    def _execute_impl(self, memo: Dict[int, Any], dag_input: Any) -> Any:
        raise NotImplementedError

    def execute(self, dag_input: Any = None) -> Any:
        """Run the graph; returns this node's result (an ObjectRef for
        task/method nodes — ray_tpu.get() it)."""
        return self._execute_memo({}, dag_input)


class InputNode(DAGNode):
    """Placeholder for the value passed to execute() (reference
    input_node.py)."""

    def __init__(self) -> None:
        super().__init__((), {})

    def _execute_impl(self, memo, dag_input):
        return dag_input

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        pass


class FunctionNode(DAGNode):
    """A @remote function bound into the graph (reference
    function_node.py)."""

    def __init__(self, remote_fn: Any, args: tuple,
                 kwargs: Dict[str, Any]):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _execute_impl(self, memo, dag_input):
        args, kwargs = self._resolve_args(memo, dag_input)
        return self._remote_fn.remote(*args, **kwargs)

    @property
    def name(self) -> str:
        return getattr(self._remote_fn, "_fn", self._remote_fn).__name__


class ClassNode(DAGNode):
    """An actor class bound into the graph (reference class_node.py);
    attribute access yields bindable methods."""

    def __init__(self, actor_cls: Any, args: tuple,
                 kwargs: Dict[str, Any]):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls

    def _execute_impl(self, memo, dag_input):
        args, kwargs = self._resolve_args(memo, dag_input)
        return self._actor_cls.remote(*args, **kwargs)

    def __getattr__(self, method_name: str) -> "_BindableMethod":
        if method_name.startswith("_"):
            raise AttributeError(method_name)
        return _BindableMethod(self, method_name)


class _BindableMethod:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args: Any, **kwargs: Any) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method_name,
                               args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method_name: str,
                 args: tuple, kwargs: Dict[str, Any]):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method_name = method_name

    def _children(self) -> List[DAGNode]:
        return super()._children() + [self._class_node]

    def _execute_impl(self, memo, dag_input):
        actor = self._class_node._execute_memo(memo, dag_input)
        args, kwargs = self._resolve_args(memo, dag_input)
        return getattr(actor, self._method_name).remote(*args, **kwargs)


__all__ = ["DAGNode", "InputNode", "FunctionNode", "ClassNode",
           "ClassMethodNode"]
