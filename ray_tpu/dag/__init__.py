"""ray_tpu.dag: lazy task graphs (DAGNode API).

reference parity: python/ray/dag — DAGNode (dag_node.py:23),
FunctionNode, ClassNode/ClassMethodNode, InputNode: `.bind()` builds the
graph lazily; `.execute()` walks it, submitting each node as a task (or
actor call) with upstream results passed as ObjectRefs — used by Serve
app graphs and Workflow.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

_node_counter = [0]
_counter_lock = threading.Lock()


def _next_id() -> int:
    with _counter_lock:
        _node_counter[0] += 1
        return _node_counter[0]


class DAGNode:
    """Base graph node. Subclasses define _execute_impl."""

    def __init__(self, args: tuple, kwargs: Dict[str, Any]):
        self._bound_args = args
        self._bound_kwargs = kwargs
        self._id = _next_id()

    # -- traversal -----------------------------------------------------

    def _children(self) -> List["DAGNode"]:
        out = [a for a in self._bound_args if isinstance(a, DAGNode)]
        out += [v for v in self._bound_kwargs.values()
                if isinstance(v, DAGNode)]
        return out

    def _resolve_args(self, memo: Dict[int, Any],
                      dag_input: Any) -> Tuple[tuple, Dict[str, Any]]:
        def res(x: Any) -> Any:
            if isinstance(x, DAGNode):
                return x._execute_memo(memo, dag_input)
            return x
        return (tuple(res(a) for a in self._bound_args),
                {k: res(v) for k, v in self._bound_kwargs.items()})

    def _execute_memo(self, memo: Dict[int, Any], dag_input: Any) -> Any:
        if self._id not in memo:
            memo[self._id] = self._execute_impl(memo, dag_input)
        return memo[self._id]

    def _execute_impl(self, memo: Dict[int, Any], dag_input: Any) -> Any:
        raise NotImplementedError

    def execute(self, dag_input: Any = None) -> Any:
        """Run the graph; returns this node's result (an ObjectRef for
        task/method nodes — ray_tpu.get() it)."""
        return self._execute_memo({}, dag_input)

    def experimental_compile(self) -> "CompiledDAG":
        """Pre-resolve the static parts of this graph for repeated
        execution (reference: ray.dag experimental_compile / aDAG).

        The interpreted `.execute()` re-walks the whole graph every
        call — in particular every ClassNode instantiates a FRESH
        actor (lease round trip + worker startup) per execution. A
        compiled DAG instantiates each ClassNode's actor ONCE at
        compile time and reuses it across `.execute()` calls, so a
        repeat execution costs only the method submits.

        If a cached actor dies, the next `.execute()` notices (owner-
        side death record, no RPC), tears the compiled channels down
        and falls back to the interpreted path — correct results,
        interpreted cost. `.teardown()` kills the compile-created
        actors."""
        return CompiledDAG(self)


class InputNode(DAGNode):
    """Placeholder for the value passed to execute() (reference
    input_node.py)."""

    def __init__(self) -> None:
        super().__init__((), {})

    def _execute_impl(self, memo, dag_input):
        return dag_input

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        pass


class FunctionNode(DAGNode):
    """A @remote function bound into the graph (reference
    function_node.py)."""

    def __init__(self, remote_fn: Any, args: tuple,
                 kwargs: Dict[str, Any]):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _execute_impl(self, memo, dag_input):
        args, kwargs = self._resolve_args(memo, dag_input)
        return self._remote_fn.remote(*args, **kwargs)

    @property
    def name(self) -> str:
        return getattr(self._remote_fn, "_fn", self._remote_fn).__name__


class ClassNode(DAGNode):
    """An actor class bound into the graph (reference class_node.py);
    attribute access yields bindable methods."""

    def __init__(self, actor_cls: Any, args: tuple,
                 kwargs: Dict[str, Any]):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls

    def _execute_impl(self, memo, dag_input):
        args, kwargs = self._resolve_args(memo, dag_input)
        return self._actor_cls.remote(*args, **kwargs)

    def __getattr__(self, method_name: str) -> "_BindableMethod":
        if method_name.startswith("_"):
            raise AttributeError(method_name)
        return _BindableMethod(self, method_name)


class _BindableMethod:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args: Any, **kwargs: Any) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method_name,
                               args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method_name: str,
                 args: tuple, kwargs: Dict[str, Any]):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method_name = method_name

    def _children(self) -> List[DAGNode]:
        return super()._children() + [self._class_node]

    def _execute_impl(self, memo, dag_input):
        actor = self._class_node._execute_memo(memo, dag_input)
        args, kwargs = self._resolve_args(memo, dag_input)
        return getattr(actor, self._method_name).remote(*args, **kwargs)


class CompiledDAG:
    """A DAG whose static structure was resolved once up front.

    Compilation walks the graph, instantiates every ClassNode's actor
    immediately (memoized per node id — the graph's sharing structure
    is preserved) and caches the topological order. `execute()` seeds
    the interpreter memo with the cached actor handles, so repeated
    executions skip the per-node actor-creation round trips that
    dominate the interpreted path.

    Liveness: before each execute the cached actors are checked
    against the owner's local death records (a dict lookup — the death
    pubsub keeps it current, no RPC on the hot path). Any dead actor
    invalidates the compiled plan: remaining compile-created actors
    are torn down and this and all later `execute()` calls run the
    plain interpreted path (fresh actors per execution). Explicit
    `teardown()` does the same eagerly.

    Restriction: an actor constructor whose arguments depend on
    InputNode cannot be hoisted out of `execute()`; compiling such a
    graph raises ValueError.
    """

    def __init__(self, output_node: DAGNode):
        self._output = output_node
        self._nodes = self._collect(output_node)
        class_nodes = [n for n in self._nodes if isinstance(n, ClassNode)]
        input_reachable = self._input_reachable()
        for cn in class_nodes:
            if cn._id in input_reachable:
                raise ValueError(
                    "cannot compile: actor constructor depends on "
                    "InputNode (its value is only known at execute())")
        # instantiate every actor ONCE, sharing one memo so diamond-
        # shaped graphs (two method nodes on one ClassNode) get one
        # actor, exactly like a single interpreted execution would
        seed: Dict[int, Any] = {}
        for cn in class_nodes:
            cn._execute_memo(seed, None)
        self._actor_seed = {cn._id: seed[cn._id] for cn in class_nodes}
        self._valid = True
        self._lock = threading.Lock()
        self.executions = 0
        self.fallbacks = 0

    # -- graph analysis ------------------------------------------------

    @staticmethod
    def _collect(root: DAGNode) -> List[DAGNode]:
        out: List[DAGNode] = []
        seen: set = set()
        stack = [root]
        while stack:
            n = stack.pop()
            if n._id in seen:
                continue
            seen.add(n._id)
            out.append(n)
            stack.extend(n._children())
        return out

    def _input_reachable(self) -> set:
        """Node ids whose subtree contains an InputNode."""
        reach: set = set()
        # nodes were collected root-first; children resolve before
        # parents when walked in reverse
        for n in reversed(self._nodes):
            if isinstance(n, InputNode) or any(
                    c._id in reach for c in n._children()):
                reach.add(n._id)
        return reach

    # -- execution -----------------------------------------------------

    def _actors_alive(self) -> bool:
        from ray_tpu._private import worker as worker_mod
        w = worker_mod.global_worker_or_none()
        if w is None:
            return False
        for handle in self._actor_seed.values():
            if w.core_worker.actor_is_dead(handle._actor_id):
                return False
        return True

    def execute(self, dag_input: Any = None) -> Any:
        with self._lock:
            if self._valid and not self._actors_alive():
                self._invalidate_locked()
            valid = self._valid
        if not valid:
            self.fallbacks += 1
            return self._output._execute_memo({}, dag_input)
        self.executions += 1
        memo: Dict[int, Any] = dict(self._actor_seed)
        return self._output._execute_memo(memo, dag_input)

    def _invalidate_locked(self) -> None:
        self._valid = False
        from ray_tpu import api
        for handle in self._actor_seed.values():
            try:
                api.kill(handle)
            except Exception:  # noqa: BLE001 - teardown of an already-
                # dead or unreachable actor must not mask the fallback
                pass

    def teardown(self) -> None:
        """Kill the compile-created actors and drop to interpreted
        execution for any later `execute()` calls."""
        with self._lock:
            if self._valid:
                self._invalidate_locked()


__all__ = ["DAGNode", "InputNode", "FunctionNode", "ClassNode",
           "ClassMethodNode", "CompiledDAG"]
