"""Autoscaler v2: instance-manager architecture.

reference parity: python/ray/autoscaler/v2/ — the v2 rewrite separates
(a) a CLUSTER STATUS view served by the GCS
(GcsAutoscalerStateManager, autoscaler.proto: pending resource
requests + node states), (b) a pure SCHEDULER deciding desired
instances from that status (v2/scheduler.py), and (c) an INSTANCE
MANAGER owning each instance's lifecycle state machine
(v2/instance_manager/: QUEUED -> REQUESTED -> ALLOCATED ->
RAY_RUNNING -> TERMINATING -> TERMINATED) against a cloud provider.
v1 conflates all three in StandardAutoscaler; v2's split makes each
piece testable alone — the same property here: ClusterStatusReader is
the GCS-facing piece, InstanceManager drives the provider, and
AutoscalerV2.run_once wires them through the shared demand scheduler
(demand_scheduler.get_nodes_to_launch).
"""

from __future__ import annotations

import logging
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.autoscaler import NodeProvider
from ray_tpu.autoscaler.demand_scheduler import (NodeType,
                                                 get_nodes_to_launch)

logger = logging.getLogger(__name__)

# instance lifecycle (reference v2/instance_manager/common.py states)
QUEUED = "QUEUED"
REQUESTED = "REQUESTED"
ALLOCATED = "ALLOCATED"
RAY_RUNNING = "RAY_RUNNING"
TERMINATING = "TERMINATING"
TERMINATED = "TERMINATED"


@dataclass
class Instance:
    instance_id: str
    node_type: str
    status: str = QUEUED
    provider_node: Any = None
    node_id_hex: Optional[str] = None
    launched_at: float = field(default_factory=time.time)
    status_history: List[str] = field(default_factory=list)

    def set_status(self, status: str) -> None:
        self.status_history.append(self.status)
        self.status = status


@dataclass
class ClusterStatus:
    """The GcsAutoscalerStateManager view (autoscaler.proto
    GetClusterResourceState): what the scheduler needs, nothing else."""

    pending_demands: List[Dict[str, float]] = field(default_factory=list)
    node_available: List[Dict[str, float]] = field(default_factory=list)
    alive_node_ids: List[str] = field(default_factory=list)
    busy_node_ids: List[str] = field(default_factory=list)


class ClusterStatusReader:
    """Builds ClusterStatus from the GCS + node managers (the
    in-process equivalent of the GCS autoscaler state RPC)."""

    def __init__(self, gcs_address: str):
        from ray_tpu._private import rpc as rpc_lib
        host, port = gcs_address.rsplit(":", 1)
        self._gcs = rpc_lib.RpcClient((host, int(port)), timeout=60)
        self._pool = rpc_lib.ClientPool(timeout=30)

    def read(self) -> ClusterStatus:
        status = ClusterStatus()
        try:
            nodes = [n for n in self._gcs.call("get_all_nodes")
                     if n.alive]
        except Exception:  # noqa: BLE001
            return status
        for n in nodes:
            try:
                info = self._pool.get(tuple(n.address)).call(
                    "nm_get_info")
                workers = self._pool.get(tuple(n.address)).call(
                    "nm_list_workers")
            except Exception:  # noqa: BLE001 - node died mid-poll; skip this round
                continue
            nid = n.node_id.hex()
            status.alive_node_ids.append(nid)
            status.pending_demands.extend(
                info.get("pending_resource_shapes") or [])
            status.node_available.append(
                dict(info.get("available") or {}))
            if any(not w["idle"] for w in workers):
                status.busy_node_ids.append(nid)
        return status


class InstanceManager:
    """Owns instance records and drives them through the lifecycle
    against the provider (reference v2/instance_manager)."""

    def __init__(self, provider: NodeProvider):
        self.provider = provider
        self.instances: Dict[str, Instance] = {}

    def launch(self, node_type: NodeType) -> Instance:
        inst = Instance(instance_id=uuid.uuid4().hex[:12],
                        node_type=node_type.name)
        self.instances[inst.instance_id] = inst
        inst.set_status(REQUESTED)
        try:
            node = self.provider.create_node(dict(node_type.resources))
        except Exception:  # noqa: BLE001
            logger.exception("provider launch failed for %s",
                             node_type.name)
            inst.set_status(TERMINATED)
            return inst
        inst.provider_node = node
        inst.node_id_hex = node.node_id_hex
        inst.set_status(ALLOCATED)
        return inst

    def terminate(self, inst: Instance) -> None:
        if inst.status in (TERMINATING, TERMINATED):
            return
        inst.set_status(TERMINATING)
        try:
            if inst.provider_node is not None:
                self.provider.terminate_node(inst.provider_node)
        except Exception:  # noqa: BLE001
            logger.exception("provider terminate failed for %s",
                             inst.instance_id)
        inst.set_status(TERMINATED)

    def reconcile(self, alive_node_ids: List[str]) -> None:
        """Advance ALLOCATED instances whose node joined the cluster to
        RAY_RUNNING; mark instances whose provider node vanished
        TERMINATED (reference: instance reconciler)."""
        live = {n.provider_id for n in
                self.provider.non_terminated_nodes()}
        for inst in self.instances.values():
            if inst.status == ALLOCATED and \
                    inst.node_id_hex in alive_node_ids:
                inst.set_status(RAY_RUNNING)
            elif inst.status in (ALLOCATED, RAY_RUNNING) and \
                    inst.provider_node is not None and \
                    inst.provider_node.provider_id not in live:
                inst.set_status(TERMINATED)

    def active(self) -> List[Instance]:
        return [i for i in self.instances.values()
                if i.status in (REQUESTED, ALLOCATED, RAY_RUNNING)]


class AutoscalerV2:
    """run_once: read status -> schedule -> drive the instance manager
    (reference v2 autoscaler loop)."""

    def __init__(self, status_reader: Any, provider: NodeProvider,
                 node_types: List[NodeType], *,
                 max_nodes: int = 8, idle_timeout_s: float = 30.0):
        self.reader = status_reader
        self.im = InstanceManager(provider)
        self.node_types = {t.name: t for t in node_types}
        self.max_nodes = max_nodes
        self.idle_timeout_s = idle_timeout_s
        self._idle_since: Dict[str, float] = {}

    def run_once(self) -> None:
        status: ClusterStatus = self.reader.read()
        self.im.reconcile(status.alive_node_ids)
        active = self.im.active()
        launched = 0
        unplaceable: List[Dict[str, float]] = []
        if status.pending_demands and len(active) < self.max_nodes:
            # count BOOTING instances (REQUESTED/ALLOCATED — launched
            # but not yet alive in the GCS) as existing capacity, or a
            # single pending demand re-launches a node on every tick
            # for the minutes a real node takes to boot
            booting = [dict(self.node_types[i.node_type].resources)
                       for i in active
                       if i.status in (REQUESTED, ALLOCATED)
                       and i.node_type in self.node_types]
            to_launch, unplaceable = get_nodes_to_launch(
                status.pending_demands,
                list(status.node_available) + booting,
                list(self.node_types.values()),
                max_total_nodes=self.max_nodes + 1)
            for type_name, count in to_launch.items():
                for _ in range(count):
                    if len(self.im.active()) >= self.max_nodes:
                        break
                    self.im.launch(self.node_types[type_name])
                    launched += 1
            if unplaceable:
                logger.warning("autoscaler v2: %d unplaceable demands",
                               len(unplaceable))
        if launched:
            return
        # idle scale-down: runs unless there is PLACEABLE demand
        # pressure — a permanently unplaceable demand must not pin idle
        # nodes forever
        placeable_pending = (len(status.pending_demands)
                             - len(unplaceable)) if unplaceable else \
            len(status.pending_demands)
        now = time.monotonic()
        for inst in self.im.active():
            if inst.status != RAY_RUNNING:
                continue
            busy = inst.node_id_hex in status.busy_node_ids
            if not busy and placeable_pending == 0:
                first = self._idle_since.setdefault(inst.instance_id,
                                                    now)
                if now - first >= self.idle_timeout_s:
                    self.im.terminate(inst)
                    self._idle_since.pop(inst.instance_id, None)
            else:
                self._idle_since.pop(inst.instance_id, None)
