"""Autoscaler v2: instance-manager architecture.

reference parity: python/ray/autoscaler/v2/ — the v2 rewrite separates
(a) a CLUSTER STATUS view served by the GCS
(GcsAutoscalerStateManager, autoscaler.proto: pending resource
requests + node states), (b) a pure SCHEDULER deciding desired
instances from that status (v2/scheduler.py), and (c) an INSTANCE
MANAGER owning each instance's lifecycle state machine
(v2/instance_manager/: QUEUED -> REQUESTED -> ALLOCATED ->
RAY_RUNNING -> TERMINATING -> TERMINATED) against a cloud provider.
v1 conflates all three in StandardAutoscaler; v2's split makes each
piece testable alone — the same property here: ClusterStatusReader is
the GCS-facing piece, InstanceManager drives the provider, and
AutoscalerV2.run_once wires them through the shared demand scheduler
(demand_scheduler.get_nodes_to_launch).

The lifecycle is an explicit state machine (reference
v2/instance_manager/common.py InstanceUtil.get_valid_transitions):
illegal edges raise InstanceLifecycleError at the source, provider
errors are retried on a bounded budget, instances wedged in a
non-terminal state past a per-state timeout are swept (terminated or
re-queued), and every transition is published as a lifecycle event —
both to in-process listeners and, when a GCS address is configured,
onto the "autoscaler_lifecycle" pubsub channel + the cluster event log
so elastic trainers (train/backend_executor.py) can subscribe to
membership changes.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.autoscaler.autoscaler import NodeProvider
from ray_tpu.autoscaler.demand_scheduler import (NodeType,
                                                 PlacementGroupDemand,
                                                 get_nodes_to_launch)

logger = logging.getLogger(__name__)

# instance lifecycle (reference v2/instance_manager/common.py states)
QUEUED = "QUEUED"
REQUESTED = "REQUESTED"
ALLOCATED = "ALLOCATED"
RAY_RUNNING = "RAY_RUNNING"
TERMINATING = "TERMINATING"
TERMINATED = "TERMINATED"

# the legal edge set (reference InstanceUtil.get_valid_transitions):
# REQUESTED->QUEUED is the bounded provider-error retry; *->TERMINATED
# shortcuts exist only where the instance has nothing to release
# (QUEUED never touched the provider; a vanished provider node has
# nothing left to terminate).
LEGAL_TRANSITIONS: Dict[str, frozenset] = {
    QUEUED: frozenset({REQUESTED, TERMINATED}),
    REQUESTED: frozenset({ALLOCATED, QUEUED, TERMINATED}),
    ALLOCATED: frozenset({RAY_RUNNING, TERMINATING, TERMINATED}),
    RAY_RUNNING: frozenset({TERMINATING, TERMINATED}),
    TERMINATING: frozenset({TERMINATED}),
    TERMINATED: frozenset(),
}

# how long an instance may sit in a state before the reconciler calls
# it stuck (reference reconciler stuck-instance handling): REQUESTED
# covers a wedged provider call, ALLOCATED a node that never joined
# the GCS, TERMINATING a wedged teardown. 0/None disables a state's
# sweep. QUEUED has no timeout: queued instances are retried by
# drive() on its own budget.
DEFAULT_STUCK_TIMEOUTS: Dict[str, float] = {
    REQUESTED: 120.0,
    ALLOCATED: 300.0,
    TERMINATING: 60.0,
}


class InstanceLifecycleError(RuntimeError):
    """An illegal lifecycle edge was requested (bug at the call site)."""


@dataclass
class Instance:
    instance_id: str
    node_type: str
    status: str = QUEUED
    provider_node: Any = None
    node_id_hex: Optional[str] = None
    launched_at: float = field(default_factory=time.time)
    # previous statuses, oldest first (plain strings; the full records
    # live in `transitions`)
    status_history: List[str] = field(default_factory=list)
    transitions: List[Dict[str, Any]] = field(default_factory=list)
    retries: int = 0
    state_since: float = field(default_factory=time.monotonic)

    def set_status(self, status: str, reason: str = "") -> Dict[str, Any]:
        if status not in LEGAL_TRANSITIONS:
            raise InstanceLifecycleError(
                f"unknown instance status {status!r}")
        if status not in LEGAL_TRANSITIONS[self.status]:
            raise InstanceLifecycleError(
                f"illegal lifecycle edge {self.status} -> {status} for "
                f"instance {self.instance_id} ({self.node_type})")
        record = {
            "instance_id": self.instance_id,
            "node_type": self.node_type,
            "from": self.status,
            "to": status,
            "reason": reason,
            "node_id_hex": self.node_id_hex,
            "ts": time.time(),
        }
        self.status_history.append(self.status)
        self.transitions.append(record)
        self.status = status
        self.state_since = time.monotonic()
        return record

    def age_in_state(self) -> float:
        return time.monotonic() - self.state_since

    def to_dict(self) -> Dict[str, Any]:
        return {
            "instance_id": self.instance_id,
            "node_type": self.node_type,
            "status": self.status,
            "node_id_hex": self.node_id_hex,
            "launched_at": self.launched_at,
            "retries": self.retries,
            "age_in_state_s": round(self.age_in_state(), 3),
            "status_history": list(self.status_history),
        }


@dataclass
class ClusterStatus:
    """The GcsAutoscalerStateManager view (autoscaler.proto
    GetClusterResourceState): what the scheduler needs, nothing else."""

    pending_demands: List[Dict[str, float]] = field(default_factory=list)
    node_available: List[Dict[str, float]] = field(default_factory=list)
    alive_node_ids: List[str] = field(default_factory=list)
    busy_node_ids: List[str] = field(default_factory=list)


class ClusterStatusReader:
    """Builds ClusterStatus from the GCS + node managers (the
    in-process equivalent of the GCS autoscaler state RPC). Pending
    demand covers BOTH queued worker leases (per-NM
    pending_resource_shapes) and PENDING placement groups (the gang
    demand an elastic trainer's unscheduled replacement-probe bundles
    produce — reference: the v2 cluster resource state carries
    gang_resource_requests)."""

    def __init__(self, gcs_address: str, *,
                 nm_unreachable_rounds: int = 3):
        from ray_tpu._private import rpc as rpc_lib
        host, port = gcs_address.rsplit(":", 1)
        self._gcs = rpc_lib.RpcClient((host, int(port)), timeout=60)
        self._pool = rpc_lib.ClientPool(timeout=30)
        # consecutive failed NM polls before a GCS-alive node reads as
        # cluster-dead: ONE transient RPC timeout must not feed the
        # zombie sweep (it would terminate a healthy host and its
        # gang), but a sustained partition still must — the GCS's own
        # health probes may not share the reader's network vantage
        self.nm_unreachable_rounds = nm_unreachable_rounds
        self._nm_fail_rounds: Dict[str, int] = {}

    def read(self) -> ClusterStatus:
        status = ClusterStatus()
        try:
            nodes = [n for n in self._gcs.call("get_all_nodes")
                     if n.alive]
        except Exception:  # noqa: BLE001
            return status
        # fail streaks are only meaningful for nodes the GCS currently
        # lists: a node that left and re-registered (blip) must start a
        # fresh streak, and counters for long-gone nodes must not
        # accumulate into a later same-id node's verdict (or leak)
        seen = {n.node_id.hex() for n in nodes}
        for stale in [nid for nid in self._nm_fail_rounds
                      if nid not in seen]:
            del self._nm_fail_rounds[stale]
        for n in nodes:
            nid = n.node_id.hex()
            try:
                info = self._pool.get(tuple(n.address)).call(
                    "nm_get_info")
                workers = self._pool.get(tuple(n.address)).call(
                    "nm_list_workers")
            except Exception:  # noqa: BLE001 - NM unreachable
                fails = self._nm_fail_rounds.get(nid, 0) + 1
                self._nm_fail_rounds[nid] = fails
                if fails < self.nm_unreachable_rounds:
                    # transient: still alive, contribute no demand or
                    # availability, and count the node busy — idle
                    # scale-down must not reap a node it could not
                    # actually observe idle
                    status.alive_node_ids.append(nid)
                    status.busy_node_ids.append(nid)
                # else: sustained unreachability — omit from the alive
                # set so reconcile() can reclaim the zombie
                continue
            self._nm_fail_rounds.pop(nid, None)
            status.alive_node_ids.append(nid)
            status.pending_demands.extend(
                info.get("pending_resource_shapes") or [])
            status.node_available.append(
                dict(info.get("available") or {}))
            if any(not w["idle"] for w in workers):
                status.busy_node_ids.append(nid)
        try:
            groups = self._gcs.call("list_placement_groups")
        except Exception:  # noqa: BLE001 - older GCS; PG demand unavailable
            groups = []
        for info in groups:
            if getattr(info, "state", None) != "PENDING":
                continue
            demand = PlacementGroupDemand(
                bundles=[dict(b) for b in info.bundles],
                strategy=getattr(info, "strategy", "PACK"))
            status.pending_demands.extend(demand.expand())
        return status


class InstanceManager:
    """Owns instance records and drives them through the lifecycle
    against the provider (reference v2/instance_manager): QUEUED
    instances are pumped through the provider by drive() on a bounded
    retry budget, reconcile() advances/retires instances from the
    cluster's point of view and sweeps stuck states, and every
    transition is fanned out to lifecycle listeners."""

    def __init__(self, provider: NodeProvider, *,
                 max_launch_retries: int = 2,
                 stuck_timeouts: Optional[Dict[str, float]] = None,
                 on_event: Optional[Callable[[Dict[str, Any]], None]] = None):
        self.provider = provider
        self.instances: Dict[str, Instance] = {}
        self.max_launch_retries = max_launch_retries
        self.stuck_timeouts = dict(DEFAULT_STUCK_TIMEOUTS)
        if stuck_timeouts:
            self.stuck_timeouts.update(stuck_timeouts)
        self._listeners: List[Callable[[Dict[str, Any]], None]] = []
        if on_event is not None:
            self._listeners.append(on_event)

    # ---- events -----------------------------------------------------
    def add_listener(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        self._listeners.append(fn)

    def _transition(self, inst: Instance, status: str,
                    reason: str = "") -> None:
        record = inst.set_status(status, reason)
        for fn in list(self._listeners):
            try:
                fn(record)
            except Exception:  # noqa: BLE001 - a broken listener must not
                logger.exception("lifecycle listener failed")  # stall scaling

    # ---- launch path ------------------------------------------------
    def request(self, node_type: NodeType) -> Instance:
        """Enqueue a launch (QUEUED); drive()/launch() pump it through
        the provider."""
        inst = Instance(instance_id=uuid.uuid4().hex[:12],
                        node_type=node_type.name)
        self.instances[inst.instance_id] = inst
        return inst

    def launch(self, node_type: NodeType) -> Instance:
        """request + one synchronous drive attempt (the v1-compatible
        entry point; failures stay QUEUED for later drive() retries
        while budget remains)."""
        inst = self.request(node_type)
        self._drive_instance(inst, node_type)
        return inst

    def _drive_instance(self, inst: Instance,
                        node_type: NodeType) -> None:
        self._transition(inst, REQUESTED, "launch requested")
        try:
            node = self.provider.create_node(dict(node_type.resources))
        except Exception as e:  # noqa: BLE001
            inst.retries += 1
            if inst.retries > self.max_launch_retries:
                logger.exception(
                    "provider launch failed for %s; retry budget "
                    "(%d) exhausted", node_type.name,
                    self.max_launch_retries)
                self._transition(
                    inst, TERMINATED,
                    f"provider error after {inst.retries} attempts: "
                    f"{e!r}")
            else:
                logger.warning(
                    "provider launch failed for %s (attempt %d/%d): "
                    "%r; re-queued", node_type.name, inst.retries,
                    self.max_launch_retries + 1, e)
                self._transition(
                    inst, QUEUED,
                    f"provider error (attempt {inst.retries}): {e!r}")
            return
        inst.provider_node = node
        inst.node_id_hex = node.node_id_hex
        self._transition(inst, ALLOCATED, "provider node created")

    def drive(self, node_types: Dict[str, NodeType]) -> None:
        """Pump QUEUED instances (provider-error retries) whose type is
        still known."""
        for inst in list(self.instances.values()):
            if inst.status != QUEUED:
                continue
            node_type = node_types.get(inst.node_type)
            if node_type is None:
                self._transition(inst, TERMINATED,
                                 "node type no longer configured")
                continue
            self._drive_instance(inst, node_type)

    # ---- teardown path ----------------------------------------------
    def terminate(self, inst: Instance, reason: str = "") -> None:
        if inst.status in (TERMINATING, TERMINATED):
            return
        if inst.status in (QUEUED, REQUESTED):
            # never touched / never got a provider node: nothing to
            # release
            self._transition(inst, TERMINATED,
                             reason or "terminated before allocation")
            return
        self._transition(inst, TERMINATING, reason)
        try:
            if inst.provider_node is not None:
                self.provider.terminate_node(inst.provider_node)
        except Exception:  # noqa: BLE001
            logger.exception("provider terminate failed for %s",
                             inst.instance_id)
            # stay TERMINATING: transitioning to TERMINATED would
            # record a clean release for a node the provider still
            # runs (and bills). reconcile() retries the release each
            # pass while the provider lists the node; the TERMINATING
            # stuck-sweep is the forced backstop.
            return
        self._transition(inst, TERMINATED, reason)

    # ---- reconcile --------------------------------------------------
    def reconcile(self, alive_node_ids: List[str]) -> None:
        """Advance ALLOCATED instances whose node joined the cluster to
        RAY_RUNNING; mark instances whose provider node vanished
        TERMINATED; sweep instances stuck in a non-terminal state past
        their per-state timeout (reference: instance reconciler)."""
        live = {n.provider_id for n in
                self.provider.non_terminated_nodes()}
        for inst in list(self.instances.values()):
            if inst.status == ALLOCATED and \
                    inst.node_id_hex in alive_node_ids:
                self._transition(inst, RAY_RUNNING,
                                 "node joined the cluster")
            elif inst.status in (ALLOCATED, RAY_RUNNING, TERMINATING) \
                    and inst.provider_node is not None and \
                    inst.provider_node.provider_id not in live:
                self._transition(inst, TERMINATED,
                                 "provider node vanished")
            elif inst.status == TERMINATING and \
                    inst.provider_node is not None and \
                    inst.provider_node.provider_id in live:
                # a terminate whose provider call failed: retry the
                # release each pass until the node actually leaves
                try:
                    self.provider.terminate_node(inst.provider_node)
                except Exception:  # noqa: BLE001 - provider still
                    logger.warning(   # failing; next pass retries
                        "provider terminate retry failed for %s",
                        inst.instance_id)
                else:
                    self._transition(inst, TERMINATED,
                                     "released on retry")
            elif inst.status == RAY_RUNNING and alive_node_ids and \
                    inst.node_id_hex not in alive_node_ids:
                # the cluster declared the node dead (health checks)
                # while the provider still lists it — a zombie host
                # (partitioned / preempted mid-teardown): release it so
                # its capacity can be replaced. Guarded on a non-empty
                # alive set: a failed status read must not mass-
                # terminate the fleet.
                self.terminate(inst, "cluster reports node dead")
        self._sweep_stuck()
        self._prune_terminated()

    def _sweep_stuck(self) -> None:
        for inst in list(self.instances.values()):
            timeout = self.stuck_timeouts.get(inst.status)
            if not timeout or inst.age_in_state() < timeout:
                continue
            reason = (f"stuck in {inst.status} for "
                      f"{inst.age_in_state():.0f}s (> {timeout:.0f}s)")
            if inst.status == TERMINATING:
                # teardown wedged: the provider call already ran (or
                # raised); stop waiting on it
                self._transition(inst, TERMINATED, reason)
            elif inst.status == ALLOCATED and \
                    inst.retries < self.max_launch_retries:
                # node never joined the GCS: release it and re-queue a
                # replacement carrying the retry budget forward
                self.terminate(inst, reason)
                replacement = Instance(
                    instance_id=uuid.uuid4().hex[:12],
                    node_type=inst.node_type,
                    retries=inst.retries + 1)
                self.instances[replacement.instance_id] = replacement
            else:
                self.terminate(inst, reason)

    # retain only this many TERMINATED records: the table would
    # otherwise grow one permanent entry (with full transition history,
    # re-pickled to the GCS every poll pass) per preemption/idle flap
    # for the life of the autoscaler
    MAX_TERMINATED_KEPT = 64

    def _prune_terminated(self) -> None:
        dead = [i for i in self.instances.values()
                if i.status == TERMINATED]
        if len(dead) <= self.MAX_TERMINATED_KEPT:
            return
        dead.sort(key=lambda i: i.state_since)
        for inst in dead[:-self.MAX_TERMINATED_KEPT]:
            del self.instances[inst.instance_id]

    # ---- views ------------------------------------------------------
    def active(self) -> List[Instance]:
        return [i for i in self.instances.values()
                if i.status in (QUEUED, REQUESTED, ALLOCATED,
                                RAY_RUNNING)]

    def snapshot(self) -> List[Dict[str, Any]]:
        return [i.to_dict() for i in self.instances.values()]


class AutoscalerV2:
    """run_once: read status -> reconcile/drive the instance manager ->
    schedule -> launch/terminate (reference v2 autoscaler loop).
    start()/stop() run the loop on a thread. With `gcs_address` set,
    lifecycle transitions and the instance table are reported to the
    GCS (`autoscaler_v2_report`): events land in the cluster event log
    and on the "autoscaler_lifecycle" pubsub channel, the table behind
    `ray_tpu autoscaler` / util.state.autoscaler_instances() /
    /api/autoscaler."""

    def __init__(self, status_reader: Any, provider: NodeProvider,
                 node_types: List[NodeType], *,
                 max_nodes: int = 8, idle_timeout_s: float = 30.0,
                 gcs_address: Optional[str] = None,
                 max_launch_retries: int = 2,
                 stuck_timeouts: Optional[Dict[str, float]] = None,
                 poll_period_s: float = 2.0):
        self.reader = status_reader
        self.im = InstanceManager(
            provider, max_launch_retries=max_launch_retries,
            stuck_timeouts=stuck_timeouts,
            on_event=self._on_lifecycle_event)
        self.node_types = {t.name: t for t in node_types}
        self.max_nodes = max_nodes
        self.idle_timeout_s = idle_timeout_s
        self.poll_period_s = poll_period_s
        self._idle_since: Dict[str, float] = {}
        self._pending_events: List[Dict[str, Any]] = []
        self._events_lock = threading.Lock()
        self._gcs = None
        if gcs_address:
            from ray_tpu._private import rpc as rpc_lib
            host, port = gcs_address.rsplit(":", 1)
            self._gcs = rpc_lib.RpcClient((host, int(port)), timeout=30)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- lifecycle-event fan-out ------------------------------------
    def _on_lifecycle_event(self, record: Dict[str, Any]) -> None:
        with self._events_lock:
            self._pending_events.append(record)

    def _report(self) -> None:
        """Ship buffered lifecycle events + the instance table to the
        GCS in one RPC per pass (batched: a scale-up of N nodes is one
        report, not N)."""
        if self._gcs is None:
            with self._events_lock:
                self._pending_events.clear()
            return
        with self._events_lock:
            events, self._pending_events = self._pending_events, []
        try:
            self._gcs.call("autoscaler_v2_report",
                           instances=self.im.snapshot(), events=events)
        except Exception:  # noqa: BLE001 - reporting is best-effort;
            # the next pass re-ships the full instance table — but the
            # EVENTS are deltas (event log, lifecycle pubsub a trainer
            # may be waiting on), so put them back for the next pass,
            # drop-oldest bounded in case the GCS stays down
            logger.warning("autoscaler v2: state report failed",
                           exc_info=True)
            with self._events_lock:
                self._pending_events[:0] = events
                del self._pending_events[:-512]

    # ---- loop -------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscaler-v2")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_period_s):
            try:
                self.run_once()
            except Exception:  # noqa: BLE001
                logger.exception("autoscaler v2 iteration failed")

    def run_once(self) -> None:
        status: ClusterStatus = self.reader.read()
        self.im.reconcile(status.alive_node_ids)
        self.im.drive(self.node_types)  # provider-error retries
        active = self.im.active()
        launched = 0
        unplaceable: List[Dict[str, float]] = []
        if status.pending_demands and len(active) < self.max_nodes:
            # count BOOTING instances (QUEUED/REQUESTED/ALLOCATED —
            # launched but not yet alive in the GCS) as existing
            # capacity, or a single pending demand re-launches a node
            # on every tick for the minutes a real node takes to boot
            booting = [dict(self.node_types[i.node_type].resources)
                       for i in active
                       if i.status in (QUEUED, REQUESTED, ALLOCATED)
                       and i.node_type in self.node_types]
            to_launch, unplaceable = get_nodes_to_launch(
                status.pending_demands,
                list(status.node_available) + booting,
                list(self.node_types.values()),
                max_total_nodes=self.max_nodes + 1)
            for type_name, count in to_launch.items():
                for _ in range(count):
                    if len(self.im.active()) >= self.max_nodes:
                        break
                    self.im.launch(self.node_types[type_name])
                    launched += 1
            if unplaceable:
                logger.warning("autoscaler v2: %d unplaceable demands",
                               len(unplaceable))
        if launched:
            self._report()
            return
        # idle scale-down: runs unless there is PLACEABLE demand
        # pressure — a permanently unplaceable demand must not pin idle
        # nodes forever. Guarded on a non-empty alive set like the
        # zombie sweep: a failed status read (GCS outage) yields an
        # EMPTY ClusterStatus whose busy/demand silence would read as
        # "everything idle" and terminate the whole fleet.
        if not status.alive_node_ids:
            self._report()
            return
        placeable_pending = (len(status.pending_demands)
                             - len(unplaceable)) if unplaceable else \
            len(status.pending_demands)
        now = time.monotonic()
        for inst in self.im.active():
            if inst.status != RAY_RUNNING:
                continue
            busy = inst.node_id_hex in status.busy_node_ids
            if not busy and placeable_pending == 0:
                first = self._idle_since.setdefault(inst.instance_id,
                                                    now)
                if now - first >= self.idle_timeout_s:
                    self.im.terminate(
                        inst, f"idle for {self.idle_timeout_s:.0f}s")
                    self._idle_since.pop(inst.instance_id, None)
            else:
                self._idle_since.pop(inst.instance_id, None)
        self._report()
