"""StandardAutoscaler + NodeProvider implementations.

reference parity: autoscaler/_private/autoscaler.py (StandardAutoscaler:
poll load → launch/terminate through a provider), node_provider.py (the
provider ABC), fake_multi_node/node_provider.py ("nodes" are local
processes). Demand here = queued worker leases reported by node
managers; idle = a worker node with no busy workers and no queue for
idle_timeout_s.
"""

from __future__ import annotations

import json
import logging
import subprocess
import sys
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


@dataclass
class ProviderNode:
    provider_id: str
    node_id_hex: Optional[str] = None    # filled once registered in GCS
    created_at: float = field(default_factory=time.time)
    handle: Any = None                   # provider-private


class NodeProvider:
    """reference node_provider.py ABC, reduced to the scaling contract."""

    def create_node(self, resources: Dict[str, float]) -> ProviderNode:
        raise NotImplementedError

    def terminate_node(self, node: ProviderNode) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[ProviderNode]:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Nodes are `node_main` subprocesses joining the GCS (the fake-
    multinode pattern: scale tests without a cloud)."""

    def __init__(self, gcs_address: str):
        self.gcs_address = gcs_address
        self._nodes: Dict[str, ProviderNode] = {}

    def create_node(self, resources: Dict[str, float]) -> ProviderNode:
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.node_main",
             "--gcs-address", self.gcs_address,
             "--resources", json.dumps(resources)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
        line = proc.stdout.readline()
        info = json.loads(line) if line else {}
        node = ProviderNode(provider_id=uuid.uuid4().hex[:8],
                            node_id_hex=info.get("node_id"), handle=proc)
        self._nodes[node.provider_id] = node
        return node

    def terminate_node(self, node: ProviderNode) -> None:
        self._nodes.pop(node.provider_id, None)
        proc: subprocess.Popen = node.handle
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()

    def non_terminated_nodes(self) -> List[ProviderNode]:
        return [n for n in self._nodes.values()
                if n.handle.poll() is None]


class FakeMultiNodeProvider(NodeProvider):
    """Instant in-memory nodes (reference
    autoscaler/_private/fake_multi_node/node_provider.py — the testable
    fake behind AutoscalingCluster): no processes, no GCS; scaling
    logic and bin-packing are testable at zero spawn latency. Each
    fake node records the resource shape it was launched with."""

    def __init__(self):
        self._nodes: Dict[str, ProviderNode] = {}
        self.created_shapes: List[Dict[str, float]] = []

    def create_node(self, resources: Dict[str, float]) -> ProviderNode:
        node = ProviderNode(provider_id=uuid.uuid4().hex[:8],
                            node_id_hex=uuid.uuid4().hex,
                            handle=dict(resources))
        self._nodes[node.provider_id] = node
        self.created_shapes.append(dict(resources))
        return node

    def terminate_node(self, node: ProviderNode) -> None:
        self._nodes.pop(node.provider_id, None)

    def non_terminated_nodes(self) -> List[ProviderNode]:
        return list(self._nodes.values())


class SliceBackend:
    """Host-materialization hook for GKETPUNodeProvider (the seam the
    reference gets from batching_node_provider.py:54 — the provider
    asks the platform for hosts; how they appear is pluggable/testable).
    create_hosts returns one dict per host: at least
    {"host_id": ..., "node_id_hex": ... or None, "resources": {...}}."""

    def create_hosts(self, pool: str,
                     host_resources: List[Dict[str, float]]
                     ) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def delete_hosts(self, pool: str) -> None:
        raise NotImplementedError


class FakeSliceBackend(SliceBackend):
    """Instant in-memory slice hosts (the FakeMultiNode pattern): each
    host records the resource shape it registered with, so autoscaler
    tests can drive PG demand -> slice scale-up without GKE."""

    def __init__(self):
        self.hosts_by_pool: Dict[str, List[Dict[str, Any]]] = {}

    def create_hosts(self, pool, host_resources):
        hosts = [{"host_id": f"{pool}-host{i}",
                  "node_id_hex": uuid.uuid4().hex,
                  "resources": dict(res)}
                 for i, res in enumerate(host_resources)]
        self.hosts_by_pool[pool] = hosts
        return hosts

    def delete_hosts(self, pool):
        self.hosts_by_pool.pop(pool, None)


class GKESliceBackend(SliceBackend):
    """gcloud node-pool backend: one pool = one TPU slice; GKE boots
    the hosts, which join the cluster out of band (their node ids
    appear in the GCS once `ray start` runs on them)."""

    def __init__(self, cluster: str, zone: str, machine_type: str,
                 topology_for):
        self.cluster = cluster
        self.zone = zone
        self.machine_type = machine_type
        self._topology_for = topology_for

    def _run(self, args: List[str]) -> str:
        proc = subprocess.run(["gcloud", *args], capture_output=True,
                              text=True, timeout=300)
        if proc.returncode != 0:
            raise RuntimeError(
                f"gcloud {' '.join(args[:3])}... failed: "
                f"{proc.stderr[-500:]}")
        return proc.stdout

    def create_hosts(self, pool, host_resources):
        chips = int(sum(r.get("TPU", 0) for r in host_resources))
        self._run([
            "container", "node-pools", "create", pool,
            f"--cluster={self.cluster}", f"--zone={self.zone}",
            f"--num-nodes={len(host_resources)}",
            f"--machine-type={self.machine_type}",
            f"--tpu-topology={self._topology_for(chips)}",
        ])
        return [{"host_id": f"{pool}-host{i}", "node_id_hex": None,
                 "resources": dict(res)}
                for i, res in enumerate(host_resources)]

    def delete_hosts(self, pool):
        self._run([
            "container", "node-pools", "delete", pool,
            f"--cluster={self.cluster}", f"--zone={self.zone}",
            "--quiet"])


class GKETPUNodeProvider(NodeProvider):
    """GKE TPU node-pool provider: one provider "node" = one TPU pod
    SLICE (a node pool with `tpu-topology`), materialized as one host
    per TPU VM. Follows the reference provider contract
    (node_provider.py) + the TPU accelerator manager's pod-slice
    resource naming (accelerators/tpu.py:335-398): every host carries
    {"TPU": <chips/host>, "<pool>": 1}, and host 0 additionally
    carries {"TPU-<type>-head": 1} so a gang's head actor (the jax
    coordinator) lands exactly once per slice.

    `backend` is the host-materialization seam: GKESliceBackend runs
    gcloud (production); FakeSliceBackend materializes instant hosts
    (the fake-multinode test ladder, reference
    batching_node_provider.py:54 pattern).
    """

    CHIPS_PER_HOST = 4  # v5p TPU-VM hosts

    def __init__(self, cluster: str = "", zone: str = "",
                 accelerator_type: str = "v5p-8",
                 node_pool_prefix: str = "ray-tpu",
                 backend: Optional[SliceBackend] = None):
        self.cluster = cluster
        self.zone = zone
        self.accelerator_type = accelerator_type
        self.node_pool_prefix = node_pool_prefix
        self.backend = backend or GKESliceBackend(
            cluster, zone, "ct5p-hightpu-4t", self._topology_for)
        self._nodes: Dict[str, ProviderNode] = {}

    # TensorCores per chip by TPU generation: the accelerator-type
    # suffix counts CORES for v2-v5p (so "v5p-16" is 8 chips) but CHIPS
    # for the single-core-per-chip generations (v5e/v5litepod, v6e).
    # Sizing pools off the raw suffix doubled every v5p node pool and
    # its --tpu-topology (ADVICE r5).
    CORES_PER_CHIP = {"v2": 2, "v3": 2, "v4": 2, "v5p": 2,
                      "v5e": 1, "v5litepod": 1, "v6e": 1}

    @property
    def slice_chips(self) -> int:
        try:
            gen, suffix = self.accelerator_type.rsplit("-", 1)
            n = int(suffix)
        except (IndexError, ValueError):
            return self.CHIPS_PER_HOST
        return max(1, n // self.CORES_PER_CHIP.get(gen.lower(), 1))

    def _host_resources(self, pool: str) -> List[Dict[str, float]]:
        n_hosts = max(1, self.slice_chips // self.CHIPS_PER_HOST)
        out = []
        for i in range(n_hosts):
            res: Dict[str, float] = {
                "TPU": float(min(self.CHIPS_PER_HOST, self.slice_chips)),
                pool: 1.0,
            }
            if i == 0:
                res[f"TPU-{self.accelerator_type}-head"] = 1.0
            out.append(res)
        return out

    def create_node(self, resources: Dict[str, float]) -> ProviderNode:
        pool = f"{self.node_pool_prefix}-{uuid.uuid4().hex[:6]}"
        hosts = self.backend.create_hosts(pool,
                                          self._host_resources(pool))
        node = ProviderNode(
            provider_id=pool,
            node_id_hex=hosts[0].get("node_id_hex"),
            handle={"pool": pool, "hosts": hosts})
        self._nodes[pool] = node
        return node

    @staticmethod
    def _topology_for(chips: int) -> str:
        # v5p topologies: 4 chips per host; topology and --num-nodes
        # derive from the same chip count — reject sizes we can't spell
        # rather than emitting an inconsistent pool spec
        hosts = max(1, chips // 4)
        topo = {1: "2x2x1", 2: "2x2x2", 4: "2x2x4", 8: "2x4x4",
                16: "4x4x4"}.get(hosts)
        if topo is None:
            raise ValueError(
                f"unsupported v5p slice size: {chips} chips "
                f"({hosts} hosts); supported hosts: 1,2,4,8,16")
        return topo

    def terminate_node(self, node: ProviderNode) -> None:
        self.backend.delete_hosts(node.provider_id)
        self._nodes.pop(node.provider_id, None)

    def non_terminated_nodes(self) -> List[ProviderNode]:
        return list(self._nodes.values())


class StandardAutoscaler:
    """Polls cluster load via the GCS; scales worker nodes between
    min_workers and max_workers. Scale-up when leases are queued anywhere
    (work the current nodes can't place); scale-down when a provider node
    sits idle past idle_timeout_s."""

    def __init__(self, gcs_address: str, provider: NodeProvider, *,
                 resources_per_node: Optional[Dict[str, float]] = None,
                 node_types: Optional[List[Any]] = None,
                 min_workers: int = 0, max_workers: int = 4,
                 idle_timeout_s: float = 30.0, poll_period_s: float = 2.0,
                 load_fn: Optional[Any] = None):
        from ray_tpu._private import rpc as rpc_lib
        from ray_tpu.autoscaler.demand_scheduler import NodeType

        if gcs_address:
            host, port = gcs_address.rsplit(":", 1)
            self._gcs = rpc_lib.RpcClient((host, int(port)), timeout=60)
        else:
            self._gcs = None  # test mode: load injected via load_fn
        self._pool = rpc_lib.ClientPool(timeout=30)
        self.provider = provider
        self.resources_per_node = dict(resources_per_node or {"CPU": 2.0})
        # heterogeneous launchable shapes for the demand scheduler
        # (reference available_node_types); default: one type matching
        # resources_per_node
        self.node_types = list(node_types or [
            NodeType("default", dict(self.resources_per_node),
                     max_workers=max_workers)])
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.idle_timeout_s = idle_timeout_s
        self.poll_period_s = poll_period_s
        self._load_fn = load_fn
        self._idle_since: Dict[str, float] = {}
        self.num_scale_ups = 0
        self.num_scale_downs = 0
        self.last_unplaceable: List[Dict[str, float]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def _cluster_load(self) -> Dict[str, Any]:
        """Queued lease shapes, per-node availability, busy workers."""
        out: Dict[str, Any] = {"pending": 0, "pending_shapes": [],
                               "available": [], "busy_by_node": {}}
        if self._load_fn is not None:
            out.update(self._load_fn())
            out["pending"] = max(out.get("pending", 0),
                                 len(out.get("pending_shapes", [])))
            return out
        try:
            nodes = [n for n in self._gcs.call("get_all_nodes") if n.alive]
        except Exception:  # noqa: BLE001
            return out
        for n in nodes:
            try:
                info = self._pool.get(tuple(n.address)).call("nm_get_info")
                workers = self._pool.get(tuple(n.address)).call(
                    "nm_list_workers")
            except Exception:  # noqa: BLE001 - node died mid-poll; skip this round
                continue
            out["pending"] += info.get("num_pending_leases", 0)
            out["pending_shapes"].extend(
                info.get("pending_resource_shapes") or [])
            out["available"].append(dict(info.get("available") or {}))
            out["busy_by_node"][n.node_id.hex()] = sum(
                1 for w in workers if not w["idle"])
        return out

    def run_once(self) -> None:
        from ray_tpu.autoscaler.demand_scheduler import get_nodes_to_launch
        load = self._cluster_load()
        nodes = self.provider.non_terminated_nodes()
        # ---- scale up: bin-pack unplaced demand into candidate node
        # shapes (reference resource_demand_scheduler.py) -------------
        shapes = list(load.get("pending_shapes") or [])
        if not shapes and load["pending"]:
            # older node managers report counts only: assume 1-CPU tasks
            shapes = [{"CPU": 1.0}] * int(load["pending"])
        if len(nodes) < self.min_workers:
            # node-COUNT floor, not capacity demand: launch directly
            # (head-node availability must not satisfy min_workers)
            self.provider.create_node(dict(self.resources_per_node))
            self.num_scale_ups += 1
            self._emit("AUTOSCALER_SCALE_UP",
                       f"below min_workers={self.min_workers}",
                       nodes_before=len(nodes))
            return
        if shapes and len(nodes) < self.max_workers:
            to_launch, unplaceable = get_nodes_to_launch(
                shapes, list(load.get("available") or []),
                self.node_types,
                max_total_nodes=self.max_workers + 1)  # +1: head node
            self.last_unplaceable = unplaceable
            launched = 0
            for type_name, count in to_launch.items():
                t = next(t for t in self.node_types
                         if t.name == type_name)
                for _ in range(count):
                    if len(self.provider.non_terminated_nodes()) >= \
                            self.max_workers:
                        break
                    logger.info(
                        "autoscaler: launching %s for %d queued "
                        "demands", type_name, len(shapes))
                    self.provider.create_node(dict(t.resources))
                    self.num_scale_ups += 1
                    launched += 1
            if launched:
                self._emit("AUTOSCALER_SCALE_UP",
                           f"{len(shapes)} queued demands -> "
                           f"{launched} nodes",
                           nodes_before=len(nodes))
                return
        # ---- scale down idle provider nodes ------------------------
        now = time.monotonic()
        for node in nodes:
            if len(self.provider.non_terminated_nodes()) <= \
                    self.min_workers:
                break
            busy = load["busy_by_node"].get(node.node_id_hex, 0)
            if busy == 0 and load["pending"] == 0:
                first_idle = self._idle_since.setdefault(
                    node.provider_id, now)
                if now - first_idle >= self.idle_timeout_s:
                    logger.info("autoscaler: terminating idle node %s",
                                node.provider_id)
                    self._emit("AUTOSCALER_SCALE_DOWN",
                               f"node {node.provider_id} idle "
                               f"{self.idle_timeout_s:.0f}s")
                    self.provider.terminate_node(node)
                    self._idle_since.pop(node.provider_id, None)
                    self.num_scale_downs += 1
            else:
                self._idle_since.pop(node.provider_id, None)

    def _emit(self, event_type: str, message: str, **fields) -> None:
        if self._gcs is None:  # provider-only test mode
            return
        from ray_tpu._private.events import emit_via
        emit_via(self._gcs.call, "autoscaler", event_type, message,
                 **fields)

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_period_s):
            try:
                self.run_once()
            except Exception:  # noqa: BLE001
                logger.exception("autoscaler iteration failed")
