"""StandardAutoscaler + NodeProvider implementations.

reference parity: autoscaler/_private/autoscaler.py (StandardAutoscaler:
poll load → launch/terminate through a provider), node_provider.py (the
provider ABC), fake_multi_node/node_provider.py ("nodes" are local
processes). Demand here = queued worker leases reported by node
managers; idle = a worker node with no busy workers and no queue for
idle_timeout_s.
"""

from __future__ import annotations

import json
import logging
import subprocess
import sys
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


@dataclass
class ProviderNode:
    provider_id: str
    node_id_hex: Optional[str] = None    # filled once registered in GCS
    created_at: float = field(default_factory=time.time)
    handle: Any = None                   # provider-private


class NodeProvider:
    """reference node_provider.py ABC, reduced to the scaling contract."""

    def create_node(self, resources: Dict[str, float]) -> ProviderNode:
        raise NotImplementedError

    def terminate_node(self, node: ProviderNode) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[ProviderNode]:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Nodes are `node_main` subprocesses joining the GCS (the fake-
    multinode pattern: scale tests without a cloud)."""

    def __init__(self, gcs_address: str):
        self.gcs_address = gcs_address
        self._nodes: Dict[str, ProviderNode] = {}

    def create_node(self, resources: Dict[str, float]) -> ProviderNode:
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.node_main",
             "--gcs-address", self.gcs_address,
             "--resources", json.dumps(resources)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
        line = proc.stdout.readline()
        info = json.loads(line) if line else {}
        node = ProviderNode(provider_id=uuid.uuid4().hex[:8],
                            node_id_hex=info.get("node_id"), handle=proc)
        self._nodes[node.provider_id] = node
        return node

    def terminate_node(self, node: ProviderNode) -> None:
        self._nodes.pop(node.provider_id, None)
        proc: subprocess.Popen = node.handle
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()

    def non_terminated_nodes(self) -> List[ProviderNode]:
        return [n for n in self._nodes.values()
                if n.handle.poll() is None]


class StandardAutoscaler:
    """Polls cluster load via the GCS; scales worker nodes between
    min_workers and max_workers. Scale-up when leases are queued anywhere
    (work the current nodes can't place); scale-down when a provider node
    sits idle past idle_timeout_s."""

    def __init__(self, gcs_address: str, provider: NodeProvider, *,
                 resources_per_node: Optional[Dict[str, float]] = None,
                 min_workers: int = 0, max_workers: int = 4,
                 idle_timeout_s: float = 30.0, poll_period_s: float = 2.0):
        from ray_tpu._private import rpc as rpc_lib

        host, port = gcs_address.rsplit(":", 1)
        self._gcs = rpc_lib.RpcClient((host, int(port)), timeout=60)
        self._pool = rpc_lib.ClientPool(timeout=30)
        self.provider = provider
        self.resources_per_node = dict(resources_per_node or {"CPU": 2.0})
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.idle_timeout_s = idle_timeout_s
        self.poll_period_s = poll_period_s
        self._idle_since: Dict[str, float] = {}
        self.num_scale_ups = 0
        self.num_scale_downs = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def _cluster_load(self) -> Dict[str, Any]:
        """Queued leases + busy workers per alive node."""
        out: Dict[str, Any] = {"pending": 0, "busy_by_node": {}}
        try:
            nodes = [n for n in self._gcs.call("get_all_nodes") if n.alive]
        except Exception:  # noqa: BLE001
            return out
        for n in nodes:
            try:
                info = self._pool.get(tuple(n.address)).call("nm_get_info")
                workers = self._pool.get(tuple(n.address)).call(
                    "nm_list_workers")
            except Exception:  # noqa: BLE001
                continue
            out["pending"] += info.get("num_pending_leases", 0)
            out["busy_by_node"][n.node_id.hex()] = sum(
                1 for w in workers if not w["idle"])
        return out

    def run_once(self) -> None:
        load = self._cluster_load()
        nodes = self.provider.non_terminated_nodes()
        # ---- scale up (reference resource_demand_scheduler: demand the
        # cluster can't place right now → launch) --------------------
        if (load["pending"] > 0 or len(nodes) < self.min_workers) \
                and len(nodes) < self.max_workers:
            logger.info("autoscaler: %d queued leases, launching node "
                        "(%d -> %d)", load["pending"], len(nodes),
                        len(nodes) + 1)
            self._emit("AUTOSCALER_SCALE_UP",
                       f"{load['pending']} queued leases",
                       nodes_before=len(nodes))
            self.provider.create_node(self.resources_per_node)
            self.num_scale_ups += 1
            return
        # ---- scale down idle provider nodes ------------------------
        now = time.time()
        for node in nodes:
            if len(self.provider.non_terminated_nodes()) <= \
                    self.min_workers:
                break
            busy = load["busy_by_node"].get(node.node_id_hex, 0)
            if busy == 0 and load["pending"] == 0:
                first_idle = self._idle_since.setdefault(
                    node.provider_id, now)
                if now - first_idle >= self.idle_timeout_s:
                    logger.info("autoscaler: terminating idle node %s",
                                node.provider_id)
                    self._emit("AUTOSCALER_SCALE_DOWN",
                               f"node {node.provider_id} idle "
                               f"{self.idle_timeout_s:.0f}s")
                    self.provider.terminate_node(node)
                    self._idle_since.pop(node.provider_id, None)
                    self.num_scale_downs += 1
            else:
                self._idle_since.pop(node.provider_id, None)

    def _emit(self, event_type: str, message: str, **fields) -> None:
        from ray_tpu._private.events import emit_via
        emit_via(self._gcs.call, "autoscaler", event_type, message,
                 **fields)

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_period_s):
            try:
                self.run_once()
            except Exception:  # noqa: BLE001
                logger.exception("autoscaler iteration failed")
