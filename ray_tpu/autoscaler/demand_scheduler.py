"""Resource-demand bin-packing for the autoscaler.

reference parity: autoscaler/_private/resource_demand_scheduler.py —
given (a) the pending resource demands the cluster cannot place (queued
lease shapes + pending placement-group bundles) and (b) a catalog of
launchable node types, compute how many nodes of each type to launch:
first bin-pack demands onto the EXISTING nodes' available capacity
(they may just be busy momentarily), then first-fit-decreasing pack the
remainder onto virtual nodes drawn from the type catalog, preferring
the smallest type that fits each seed demand.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class NodeType:
    """One launchable shape (reference: available_node_types entries)."""

    name: str
    resources: Dict[str, float]
    max_workers: int = 100


def _fits(avail: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) >= v - 1e-9 for k, v in demand.items()
               if v > 0)


def _consume(avail: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        if v > 0:
            avail[k] = avail.get(k, 0.0) - v


def _demand_size(d: Dict[str, float]) -> Tuple[float, float]:
    # sort key: GPU/TPU-ish custom resources first, then CPU volume
    special = sum(v for k, v in d.items() if k not in ("CPU", "memory"))
    return (special, sum(d.values()))


def get_nodes_to_launch(
        pending_demands: List[Dict[str, float]],
        existing_available: List[Dict[str, float]],
        node_types: List[NodeType],
        *,
        existing_count_by_type: Optional[Dict[str, int]] = None,
        max_total_nodes: Optional[int] = None,
) -> Tuple[Dict[str, int], List[Dict[str, float]]]:
    """Return ({node_type_name: count_to_launch}, unplaceable_demands).

    First-fit-decreasing over existing capacity, then over virtual
    nodes opened from the catalog (smallest adequate type first), the
    reference scheduler's core loop
    (resource_demand_scheduler.py get_nodes_to_launch).
    """
    counts = dict(existing_count_by_type or {})
    total_existing = len(existing_available)
    avail = [dict(a) for a in existing_available]
    virtual: List[Tuple[str, Dict[str, float]]] = []
    to_launch: Dict[str, int] = {}
    unplaceable: List[Dict[str, float]] = []

    # catalog sorted smallest-first so each seed demand opens the
    # tightest-fitting node (avoids giant nodes for 1-CPU tasks)
    catalog = sorted(node_types, key=lambda t: _demand_size(t.resources))

    for demand in sorted(pending_demands, key=_demand_size, reverse=True):
        placed = False
        for a in avail:
            if _fits(a, demand):
                _consume(a, demand)
                placed = True
                break
        if placed:
            continue
        for _, a in virtual:
            if _fits(a, demand):
                _consume(a, demand)
                placed = True
                break
        if placed:
            continue
        launched = sum(to_launch.values())
        if max_total_nodes is not None and \
                total_existing + launched >= max_total_nodes:
            unplaceable.append(demand)
            continue
        for t in catalog:
            if not _fits(dict(t.resources), demand):
                continue
            if counts.get(t.name, 0) + to_launch.get(t.name, 0) \
                    >= t.max_workers:
                continue
            a = dict(t.resources)
            _consume(a, demand)
            virtual.append((t.name, a))
            to_launch[t.name] = to_launch.get(t.name, 0) + 1
            placed = True
            break
        if not placed:
            unplaceable.append(demand)
    return to_launch, unplaceable


@dataclass
class PlacementGroupDemand:
    """Pending PG bundles feed the same packer; STRICT_SPREAD bundles
    must land on distinct nodes, so they are emitted as per-bundle
    demands tagged anti-affine (reference: the scheduler's
    placement-group resource demand expansion)."""

    bundles: List[Dict[str, float]] = field(default_factory=list)
    strategy: str = "PACK"

    def expand(self) -> List[Dict[str, float]]:
        if self.strategy in ("STRICT_PACK",):
            # one node must fit the whole group: merge bundles
            merged: Dict[str, float] = {}
            for b in self.bundles:
                for k, v in b.items():
                    merged[k] = merged.get(k, 0.0) + v
            return [merged]
        return [dict(b) for b in self.bundles]
