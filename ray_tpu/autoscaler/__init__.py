"""Autoscaler: demand-driven node scale-up/down over a NodeProvider.

reference parity: python/ray/autoscaler/_private/autoscaler.py
(StandardAutoscaler + resource_demand_scheduler bin-packing over a
NodeProvider ABC) and the fake-multinode provider
(autoscaler/_private/fake_multi_node/node_provider.py) used for
provider-free testing — here LocalNodeProvider spawns real node-manager
processes on this machine.
"""

from ray_tpu.autoscaler.autoscaler import (LocalNodeProvider,  # noqa: F401
                                           NodeProvider,
                                           StandardAutoscaler)

__all__ = ["NodeProvider", "LocalNodeProvider", "StandardAutoscaler"]
