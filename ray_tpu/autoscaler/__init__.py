"""Autoscaler: demand-driven node scale-up/down over a NodeProvider.

reference parity: python/ray/autoscaler/_private/autoscaler.py
(StandardAutoscaler + resource_demand_scheduler bin-packing over a
NodeProvider ABC) and the fake-multinode provider
(autoscaler/_private/fake_multi_node/node_provider.py) used for
provider-free testing — here LocalNodeProvider spawns real node-manager
processes on this machine.
"""

from ray_tpu.autoscaler.autoscaler import (FakeMultiNodeProvider,  # noqa: F401
                                           GKETPUNodeProvider,
                                           LocalNodeProvider,
                                           NodeProvider,
                                           StandardAutoscaler)
from ray_tpu.autoscaler.demand_scheduler import (NodeType,  # noqa: F401
                                                 PlacementGroupDemand,
                                                 get_nodes_to_launch)
from ray_tpu.autoscaler.v2 import (AutoscalerV2,  # noqa: F401
                                   ClusterStatusReader, Instance,
                                   InstanceLifecycleError,
                                   InstanceManager)

__all__ = ["NodeProvider", "LocalNodeProvider", "FakeMultiNodeProvider",
           "GKETPUNodeProvider", "StandardAutoscaler", "NodeType",
           "PlacementGroupDemand", "get_nodes_to_launch",
           "AutoscalerV2", "Instance", "InstanceLifecycleError",
           "InstanceManager", "ClusterStatusReader"]
