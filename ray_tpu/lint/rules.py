"""graftlint rule set: 23 framework-aware checks.

Each rule has a stable id (RT001..RT023), a one-line rationale, and a
`check(ctx)` generator yielding Findings. Rules are deliberately
conservative: a finding should be actionable, and intentional
exceptions are silenced in-place with `# graftlint: disable=RTxxx`
comments that double as documentation.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator, List, Optional, Set

from ray_tpu.lint.engine import Finding, ModuleContext

# Calls that block the calling worker thread until remote work finishes.
BLOCKING_GET = {"ray_tpu.get", "ray.get"}
BLOCKING_WAIT = {"ray_tpu.wait", "ray.wait"}

# Host-side-effect callables that silently bake into (or retrigger) an
# XLA trace instead of running per step.
HOST_EFFECT_EXACT = {"print", "input", "open", "breakpoint"}
HOST_EFFECT_PREFIX = ("time.", "numpy.random.", "np.random.", "os.system",
                      "subprocess.", "logging.", "random.")
# jax.debug.* and jax.random are the traced-safe alternatives.
HOST_EFFECT_ALLOWED_PREFIX = ("jax.",)

MUTATING_METHODS = {"append", "extend", "add", "update", "insert",
                    "setdefault", "popitem", "clear", "remove",
                    "discard"}


class Rule:
    id: str = "RT000"
    name: str = "base"
    rationale: str = ""

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: ModuleContext, node: ast.AST,
                message: str) -> Finding:
        return Finding(self.id, ctx.path, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message)


def _blocking_calls(ctx: ModuleContext, include_wait: bool = True
                    ) -> Iterator[ast.Call]:
    names = BLOCKING_GET | (BLOCKING_WAIT if include_wait else set())
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and ctx.call_name(node) in names:
            yield node


def _in_remote_context(ctx: ModuleContext, node: ast.AST) -> Optional[str]:
    """Name of the enclosing actor method / remote function, if any."""
    fns = ctx.enclosing_functions(node)
    for fn in fns:
        if fn in ctx.remote_fns:
            return f"remote function '{getattr(fn, 'name', '<lambda>')}'"
    # a method of a @remote class: innermost non-lambda function whose
    # enclosing class is an actor class
    for fn in fns:
        cls = ctx.enclosing_class(fn)
        if cls is not None and cls in ctx.actor_classes:
            return (f"actor method "
                    f"'{cls.name}.{getattr(fn, 'name', '<lambda>')}'")
    return None


class NestedBlockingGet(Rule):
    id = "RT001"
    name = "nested-blocking-get"
    rationale = ("blocking get()/wait() inside an actor method or remote "
                 "function holds its executor thread while waiting on "
                 "other remote work - mutual calls deadlock the cluster")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for call in _blocking_calls(ctx):
            where = _in_remote_context(ctx, call)
            if where is not None:
                fn = ctx.call_name(call)
                yield self.finding(
                    ctx, call,
                    f"blocking {fn}() inside {where}: a cycle of such "
                    f"calls deadlocks (return the ObjectRef, use an "
                    f"async method, or raise max_concurrency)")


class GetInLoop(Rule):
    id = "RT002"
    name = "get-in-loop"
    rationale = ("get() in a loop serializes the trajectory plane: each "
                 "iteration round-trips before the next task is even "
                 "looked at")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for call in _blocking_calls(ctx, include_wait=False):
            loops = ctx.loops_between(call)
            if loops:
                fn = ctx.call_name(call)
                yield self.finding(
                    ctx, call,
                    f"{fn}() inside a loop serializes on each result: "
                    f"batch refs and call {fn}(refs) once, or drain "
                    f"with wait(refs) as results land")


class HostEffectInJit(Rule):
    id = "RT003"
    name = "host-side-effect-in-jit"
    rationale = ("host callables inside jit/scan bodies run once at trace "
                 "time (stale values baked in) or force retraces - use "
                 "jax.debug.print / jax.random instead")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) \
                    or not ctx.in_traced_code(node):
                continue
            name = ctx.call_name(node)
            if name is None:
                continue
            if name.startswith(HOST_EFFECT_ALLOWED_PREFIX):
                continue
            if name in HOST_EFFECT_EXACT or \
                    name.startswith(HOST_EFFECT_PREFIX):
                yield self.finding(
                    ctx, node,
                    f"host call {name}() inside a jit/scan-traced "
                    f"function executes at trace time, not per step "
                    f"(use jax.debug.print / jax.random, or hoist it "
                    f"out of the traced body)")


def _bound_names(fn: ast.AST) -> Set[str]:
    """Names bound inside a function: params, assignments, loop/with
    targets, comprehension targets, local defs."""
    bound: Set[str] = set()
    args = getattr(fn, "args", None)
    if args is not None:
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            bound.add(a.arg)
        if args.vararg:
            bound.add(args.vararg.arg)
        if args.kwarg:
            bound.add(args.kwarg.arg)

    def add_target(t: ast.AST) -> None:
        if isinstance(t, ast.Name):
            bound.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                add_target(e)
        elif isinstance(t, ast.Starred):
            add_target(t.value)

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                add_target(t)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            add_target(node.target)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            add_target(node.target)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    add_target(item.optional_vars)
        elif isinstance(node, ast.comprehension):
            add_target(node.target)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)) and node is not fn:
            bound.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                bound.add((a.asname or a.name).split(".")[0])
    return bound


class ClosureMutationInJit(Rule):
    id = "RT004"
    name = "closure-mutation-in-jit"
    rationale = ("mutating closed-over state inside a traced function "
                 "happens once at trace time - subsequent calls reuse "
                 "the compiled program and the mutation never reruns")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in ctx.traced_fns:
            if isinstance(fn, ast.Lambda):
                continue  # lambdas cannot contain statements
            bound = _bound_names(fn)
            for node in ast.walk(fn):
                if node is fn:
                    continue
                # nested defs are themselves in traced_fns; their bodies
                # report against their own (tighter) bound-name sets
                if ctx.enclosing_function(node) is not fn:
                    continue
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    yield self.finding(
                        ctx, node,
                        f"{'global' if isinstance(node, ast.Global) else 'nonlocal'} "
                        f"write inside a traced function only happens at "
                        f"trace time; thread state through the function's "
                        f"inputs/outputs instead")
                elif isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self":
                            yield self.finding(
                                ctx, t,
                                f"assignment to self.{t.attr} inside a "
                                f"traced function mutates untraced host "
                                f"state; return the new value instead")
                        elif isinstance(t, ast.Subscript) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id not in bound:
                            yield self.finding(
                                ctx, t,
                                f"item assignment on closed-over "
                                f"'{t.value.id}' inside a traced function "
                                f"is a trace-time side effect")
                elif isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr in MUTATING_METHODS and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id not in bound and \
                        node.func.value.id != "self" and \
                        isinstance(ctx.parent(node), ast.Expr):
                    # result discarded => called for the side effect;
                    # `u, s = optimizer.update(...)`-style pure APIs
                    # (optax) assign the result and are fine
                    yield self.finding(
                        ctx, node,
                        f"mutating call "
                        f"{node.func.value.id}.{node.func.attr}() on "
                        f"closed-over state inside a traced function is "
                        f"a trace-time side effect")


class ActorCallWithoutRemote(Rule):
    id = "RT005"
    name = "actor-call-without-remote"
    rationale = ("calling handle.method(...) runs nothing: actor methods "
                 "execute only via handle.method.remote(...)")

    _HANDLE_OK_ATTRS = {"remote", "options", "bind"}

    def _scope_nodes(self, fn: ast.AST, ctx: ModuleContext):
        """Nodes belonging directly to this scope (module scope must not
        re-walk function bodies — they are their own scopes)."""
        scope = None if fn is ctx.tree else fn
        for node in ast.walk(fn):
            if ctx.enclosing_function(node) is scope:
                yield node

    def _handle_names(self, fn: ast.AST, ctx: ModuleContext) -> Set[str]:
        """Names assigned from ActorClass.remote(...) /
        .options(...).remote(...) within this scope, where ActorClass
        is a @remote class defined in this module."""
        actor_names = {c.name for c in ctx.actor_classes}
        handles: Set[str] = set()
        for node in self._scope_nodes(fn, ctx):
            if not isinstance(node, ast.Assign) or \
                    not isinstance(node.value, ast.Call):
                continue
            call = node.value
            func = call.func
            if not (isinstance(func, ast.Attribute)
                    and func.attr == "remote"):
                continue
            root = func.value
            # unwrap Class.options(...).remote(...)
            if isinstance(root, ast.Call) and \
                    isinstance(root.func, ast.Attribute) and \
                    root.func.attr == "options":
                root = root.func.value
            if isinstance(root, ast.Name) and root.id in actor_names:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        handles.add(t.id)
        return handles

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        fns = [n for n in ast.walk(ctx.tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        fns.append(ctx.tree)
        for fn in fns:
            handles = self._handle_names(fn, ctx)
            if not handles:
                continue
            for node in self._scope_nodes(fn, ctx):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id in handles and \
                        node.func.attr not in self._HANDLE_OK_ATTRS and \
                        not node.func.attr.startswith("_"):
                    yield self.finding(
                        ctx, node,
                        f"'{node.func.value.id}.{node.func.attr}(...)' "
                        f"calls an actor method without .remote() - it "
                        f"raises at runtime; use "
                        f".{node.func.attr}.remote(...)")


class LeakedObjectRef(Rule):
    id = "RT006"
    name = "leaked-objectref"
    rationale = ("a .remote() result that is never stored, awaited or "
                 "passed on cannot be gotten, waited or cancelled - the "
                 "task's result (and error!) vanish")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Expr) and \
                    isinstance(node.value, ast.Call) and \
                    isinstance(node.value.func, ast.Attribute) and \
                    node.value.func.attr == "remote":
                yield self.finding(
                    ctx, node,
                    "discarded .remote() call leaks its ObjectRef: "
                    "errors are silently dropped and the result is "
                    "unreachable; keep the ref (get/wait it) or note "
                    "why fire-and-forget is safe")


class DictOrderPytree(Rule):
    id = "RT007"
    name = "dict-order-pytree"
    rationale = ("pytree construction by dict iteration inside traced "
                 "code bakes one process's insertion order into the "
                 "compiled program - ranks built in a different order "
                 "desync collectives/checkpoints; iterate sorted(...)")

    _DICT_ITERS = {"items", "keys", "values"}

    def _uses_trees(self, fn: ast.AST, ctx: ModuleContext) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = ctx.call_name(node) or ""
                if name.startswith(("jax.tree", "jax.tree_util",
                                    "tree_map", "tree_flatten")):
                    return True
        return False

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            iters: List[ast.AST] = []
            if isinstance(node, (ast.For, ast.AsyncFor)):
                iters = [node.iter]
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.DictComp, ast.GeneratorExp)):
                iters = [g.iter for g in node.generators]
            for it in iters:
                if not (isinstance(it, ast.Call)
                        and isinstance(it.func, ast.Attribute)
                        and it.func.attr in self._DICT_ITERS):
                    continue
                fn = ctx.enclosing_function(node)
                traced = ctx.in_traced_code(node)
                treey = fn is not None and self._uses_trees(fn, ctx)
                if traced or treey:
                    yield self.finding(
                        ctx, it,
                        f"pytree built by iterating .{it.func.attr}() in "
                        f"{'traced' if traced else 'tree-manipulating'} "
                        f"code depends on dict insertion order; wrap in "
                        f"sorted(...) for a rank-stable structure")


class SwallowedException(Rule):
    id = "RT008"
    name = "swallowed-exception"
    rationale = ("a bare except (or except-pass in a forever loop) eats "
                 "KeyboardInterrupt/SystemExit and turns actor-loop "
                 "crashes into silent hangs")

    def _is_forever_loop(self, node: ast.While) -> bool:
        return isinstance(node.test, ast.Constant) and \
            bool(node.test.value)

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            has_raise = any(isinstance(n, ast.Raise)
                            for n in ast.walk(node))
            if node.type is None and not has_raise:
                yield self.finding(
                    ctx, node,
                    "bare 'except:' swallows KeyboardInterrupt and "
                    "SystemExit; catch Exception (or narrower) and "
                    "log/handle it")
                continue
            # except Exception: pass  inside a while True loop: the
            # actor event-loop keeps spinning with the failure invisible
            body_is_noop = all(isinstance(n, (ast.Pass, ast.Continue))
                               for n in node.body)
            if body_is_noop and node.type is not None and \
                    ctx.dotted(node.type) in ("Exception", "BaseException"):
                in_forever = any(
                    isinstance(a, ast.While) and self._is_forever_loop(a)
                    for a in ctx.ancestors(node))
                fn_between = ctx.enclosing_function(node)
                loop_fn_ok = True
                if in_forever and fn_between is not None:
                    # the while True must be in the same function
                    loop_fn_ok = any(
                        isinstance(a, ast.While)
                        and self._is_forever_loop(a)
                        for a in ctx.ancestors(node)
                        if ctx.enclosing_function(a) is fn_between)
                if in_forever and loop_fn_ok:
                    yield self.finding(
                        ctx, node,
                        "except-and-ignore inside a forever loop hides "
                        "every failure of this event loop; at minimum "
                        "log the exception before continuing")


class StoreViewCopy(Rule):
    id = "RT009"
    name = "store-view-copy"
    rationale = ("bytes(view) / memoryview(bytes(...)) on a "
                 "store-returned buffer copies the payload and defeats "
                 "the zero-copy object plane - hold the view (pin the "
                 "object for long-lived use) instead")

    # The store implementation itself legitimately materializes bytes
    # (chunked cross-node reads, small-object RPC payloads).
    _EXEMPT_SUFFIXES = ("_private/object_store.py", "native/__init__.py")

    # attribute calls whose result is a shm-backed view when the
    # receiver is store-/arena-shaped
    _VIEW_METHODS = {"view", "pull", "get"}

    def _store_like(self, ctx: ModuleContext, node: ast.AST) -> bool:
        name = ctx.dotted(node)
        if name is None:
            # self.store.get(...): dotted() fails on self-attributes;
            # fall back to the attribute chain's text
            parts = []
            cur = node
            while isinstance(cur, ast.Attribute):
                parts.append(cur.attr)
                cur = cur.value
            if isinstance(cur, ast.Name):
                parts.append(cur.id)
            name = ".".join(reversed(parts))
        return "store" in name.lower() or "arena" in name.lower()

    def _is_view_call(self, ctx: ModuleContext, node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in self._VIEW_METHODS
                and self._store_like(ctx, node.func.value))

    def _view_names(self, fn: ast.AST, ctx: ModuleContext) -> Set[str]:
        names: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and \
                    self._is_view_call(ctx, node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
        return names

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.path.replace("\\", "/").endswith(self._EXEMPT_SUFFIXES):
            return
        view_names_cache: dict = {}
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)):
                continue
            if node.func.id == "memoryview" and node.args and \
                    isinstance(node.args[0], ast.Call) and \
                    isinstance(node.args[0].func, ast.Name) and \
                    node.args[0].func.id == "bytes":
                yield self.finding(
                    ctx, node,
                    "memoryview(bytes(...)) materializes a full copy of "
                    "the buffer; keep the original view (pin the object "
                    "if it must outlive the ref)")
                continue
            if node.func.id != "bytes" or not node.args:
                continue
            arg = node.args[0]
            # unwrap bytes(store.get([...])[oid]) / slices of a view
            while isinstance(arg, ast.Subscript):
                arg = arg.value
            if self._is_view_call(ctx, arg):
                yield self.finding(
                    ctx, node,
                    "bytes(...) over a store view copies the whole "
                    "payload out of shared memory; use the view "
                    "zero-copy (pin the object for long-lived use)")
            elif isinstance(arg, ast.Name):
                scope = ctx.enclosing_function(node) or ctx.tree
                if scope not in view_names_cache:
                    view_names_cache[scope] = self._view_names(scope, ctx)
                if arg.id in view_names_cache[scope]:
                    yield self.finding(
                        ctx, node,
                        f"bytes({arg.id}) copies a store-returned view "
                        f"out of shared memory; use it zero-copy (pin "
                        f"the object for long-lived use)")


class WallClockDuration(Rule):
    id = "RT010"
    name = "wall-clock-duration"
    rationale = ("time.time() differences measure the WALL clock, which "
                 "jumps under NTP slew/suspend - durations, deadlines "
                 "and span/metric timings must use time.monotonic() or "
                 "time.perf_counter()")

    _WALL_CALLS = {"time.time"}

    def _is_wall_call(self, ctx: ModuleContext, node: ast.AST) -> bool:
        return isinstance(node, ast.Call) and \
            ctx.call_name(node) in self._WALL_CALLS

    def _wall_names(self, scope: ast.AST, ctx: ModuleContext) -> Set[str]:
        """Names assigned (in this scope) from an expression containing a
        direct time.time() call — `t0 = time.time()`,
        `deadline = time.time() + timeout`, conditional variants."""
        names: Set[str] = set()
        for node in ast.walk(scope):
            if not isinstance(node, ast.Assign):
                continue
            if ctx.enclosing_function(node) is not (
                    None if scope is ctx.tree else scope):
                continue
            if any(self._is_wall_call(ctx, n)
                   for n in ast.walk(node.value)):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
        return names

    _ORDER_OPS = (ast.Lt, ast.LtE, ast.Gt, ast.GtE)

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        wall_names_cache: dict = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
                operands = (node.left, node.right)
            elif isinstance(node, ast.Compare) and any(
                    isinstance(op, self._ORDER_OPS) for op in node.ops):
                # Ordering comparisons are the deadline/TTL-expiry form:
                # `time.time() < deadline`, `entry_ts <= now`.
                operands = (node.left, *node.comparators)
            else:
                continue
            direct = any(self._is_wall_call(ctx, o) for o in operands)
            via_name = False
            if not direct:
                scope = ctx.enclosing_function(node) or ctx.tree
                if scope not in wall_names_cache:
                    wall_names_cache[scope] = self._wall_names(scope, ctx)
                via_name = any(isinstance(o, ast.Name)
                               and o.id in wall_names_cache[scope]
                               for o in operands)
            if direct or via_name:
                yield self.finding(
                    ctx, node,
                    "duration computed from time.time() jumps when the "
                    "wall clock is adjusted; use time.monotonic() (for "
                    "deadlines) or time.perf_counter() (for timings)")


class MetricNameConvention(Rule):
    id = "RT011"
    name = "metric-name-convention"
    rationale = ("Prometheus-convention metric names keep the merged "
                 "cluster endpoint queryable: counters end in _total, "
                 "timing/size histograms carry _seconds/_bytes units, "
                 "and per-entity id tag keys explode series cardinality")

    _METRIC_MODULES = ("ray_tpu.util.metrics.", "ray.util.metrics.")
    _KINDS = {"Counter", "Gauge", "Histogram"}
    # spellings of units that have one canonical suffix
    _BAD_UNIT_SUFFIXES = ("_ms", "_us", "_msec", "_usec", "_sec",
                          "_secs", "_time", "_kb", "_mb", "_gb",
                          "_size")
    _GOOD_HIST_SUFFIXES = ("_seconds", "_bytes")
    # tag keys whose value space grows with cluster activity: one series
    # per object/task would melt any scrape backend
    _HIGH_CARDINALITY_KEYS = {"object_id", "task_id", "actor_id",
                              "worker_id", "lease_id", "trace_id",
                              "oid", "ref", "object_ref", "pid"}

    def _metric_kind(self, ctx: ModuleContext,
                     node: ast.Call) -> Optional[str]:
        """'Counter'/'Gauge'/'Histogram' when this call constructs a
        ray_tpu metric (direct constructor or get_or_create(Cls, ...)),
        resolved through import aliases so unrelated locally-defined
        classes that happen to share a name are not flagged."""
        name = ctx.call_name(node)
        if name is None:
            return None
        if name.split(".")[-1] == "get_or_create":
            if not node.args:
                return None
            cls = ctx.dotted(node.args[0])
        else:
            cls = name
        if cls is None:
            return None
        qualified = any(s.startswith(self._METRIC_MODULES)
                        for s in (name, cls))
        if not qualified:
            return None
        kind = cls.split(".")[-1]
        return kind if kind in self._KINDS else None

    @staticmethod
    def _const_str(node: Optional[ast.AST]) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        return None

    def _call_arg(self, node: ast.Call, pos: int,
                  kw: str) -> Optional[ast.AST]:
        for k in node.keywords:
            if k.arg == kw:
                return k.value
        return node.args[pos] if len(node.args) > pos else None

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = self._metric_kind(ctx, node)
            if kind is None:
                continue
            is_factory = (ctx.call_name(node) or "").endswith(
                "get_or_create")
            name_pos = 1 if is_factory else 0
            name = self._const_str(
                self._call_arg(node, name_pos, "name"))
            if name is not None:
                if kind == "Counter" and not name.endswith("_total"):
                    yield self.finding(
                        ctx, node,
                        f"counter {name!r} must end in '_total' "
                        f"(Prometheus counter convention; rate() "
                        f"queries key on it)")
                if kind != "Counter" and name.endswith("_total"):
                    yield self.finding(
                        ctx, node,
                        f"{kind.lower()} {name!r} ends in '_total', "
                        f"which marks counters; pick a point-in-time "
                        f"name")
                if name.endswith(self._BAD_UNIT_SUFFIXES):
                    yield self.finding(
                        ctx, node,
                        f"metric {name!r} uses a non-canonical unit "
                        f"suffix; use base units '_seconds' / '_bytes'")
                elif kind == "Histogram" and not name.endswith(
                        self._GOOD_HIST_SUFFIXES):
                    yield self.finding(
                        ctx, node,
                        f"histogram {name!r} should name its unit with "
                        f"a '_seconds' or '_bytes' suffix (histograms "
                        f"measure durations or sizes)")
            # tag_keys position in the constructors: Counter/Gauge
            # (name, description, tag_keys), Histogram adds boundaries
            # before it; get_or_create passes them as kwargs only
            pos = 99 if is_factory else (3 if kind == "Histogram" else 2)
            tag_keys = self._call_arg(node, pos, "tag_keys")
            if isinstance(tag_keys, (ast.Tuple, ast.List)):
                for elt in tag_keys.elts:
                    key = self._const_str(elt)
                    if key is not None and \
                            key in self._HIGH_CARDINALITY_KEYS:
                        yield self.finding(
                            ctx, elt,
                            f"tag key {key!r} is per-entity: one "
                            f"series per {key} makes cardinality grow "
                            f"with cluster activity; aggregate or put "
                            f"the id in logs/events instead")


class BarePrintInFramework(Rule):
    id = "RT012"
    name = "bare-print-in-framework"
    rationale = ("framework diagnostics must go through `logging` so "
                 "they enter the log plane (attribution-stamped, "
                 "tail-indexed, flood-controlled — see "
                 "_private/log_plane.py); a bare print() line is "
                 "unstamped and invisible to `ray_tpu logs` filters")

    # Paths whose whole PURPOSE is writing to a terminal: tests, dev
    # tools, examples, CLI entry points. Everything else in the
    # framework tree is daemon/library code whose output lands in (or
    # should land in) worker log files.
    _EXEMPT_DIR_PARTS = frozenset(
        {"tests", "test", "tools", "examples", "benchmarks", "scripts"})

    def _exempt(self, path: str) -> bool:
        parts = [p for p in re.split(r"[\\/]", path) if p]
        if set(parts) & self._EXEMPT_DIR_PARTS:
            return True
        base = os.path.basename(path)
        return base == "__main__.py" or base.startswith("test_")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if self._exempt(ctx.path):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) \
                    and ctx.call_name(node) == "print":
                yield self.finding(
                    ctx, node,
                    "bare print() in framework code: route diagnostics "
                    "through `logging` so the line enters the log "
                    "plane with task/actor/trace attribution (stdout "
                    "sinks — CLIs, machine-readable handshakes — "
                    "suppress with `# graftlint: disable=RT012`)")


class SilentExceptionSwallow(Rule):
    id = "RT013"
    name = "silent-exception-swallow"
    rationale = ("a broad `except Exception: pass` on a framework "
                 "fan-out/state path makes partial failures invisible "
                 "— a node silently missing from a gather reads as a "
                 "healthy empty result; the handler must log the "
                 "error, record it (counter, reply field, unreachable "
                 "list), or carry a written justification of why "
                 "swallowing is correct")

    # Same surface split as RT012: code whose purpose is a terminal.
    _EXEMPT_DIR_PARTS = frozenset(
        {"tests", "test", "tools", "examples", "benchmarks", "scripts"})
    # lint-code chunks that do NOT count as justification prose
    _CODES_RE = re.compile(
        r"noqa:?\s*[A-Z0-9, ]*|graftlint:\s*disable=[A-Za-z0-9_,\s]*")

    def _exempt(self, path: str) -> bool:
        parts = [p for p in re.split(r"[\\/]", path) if p]
        if set(parts) & self._EXEMPT_DIR_PARTS:
            return True
        base = os.path.basename(path)
        return base == "__main__.py" or base.startswith("test_")

    def _prose(self, comment: str) -> bool:
        """True when the comment contains an actual explanation beyond
        lint codes — `# noqa: BLE001 - peer gone mid-collect` is a
        justified suppression, bare `# noqa: BLE001` is not."""
        text = self._CODES_RE.sub("", comment).strip(" #-—:\t")
        return len(text) >= 8 and any(c.isalpha() for c in text)

    def _justified(self, ctx: ModuleContext, node: ast.ExceptHandler
                   ) -> bool:
        end = max(s.lineno for s in node.body)
        for lineno in range(node.lineno, end + 1):
            line = ctx.source_lines[lineno - 1] \
                if lineno - 1 < len(ctx.source_lines) else ""
            if "#" in line and self._prose(line[line.index("#"):]):
                return True
        # comment-only lines directly ABOVE the except count too (the
        # idiomatic spot when the reason doesn't fit the except line)
        for lineno in range(node.lineno - 1, max(0, node.lineno - 3), -1):
            line = ctx.source_lines[lineno - 1].strip() \
                if lineno - 1 < len(ctx.source_lines) else ""
            if not line.startswith("#"):
                break
            if self._prose(line):
                return True
        return False

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if self._exempt(ctx.path):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler) \
                    or node.type is None:
                continue  # bare except is RT008's
            elts = node.type.elts if isinstance(node.type, ast.Tuple) \
                else [node.type]
            names = {ctx.dotted(e) for e in elts}
            if not names & {"Exception", "BaseException"}:
                continue
            if not all(isinstance(s, (ast.Pass, ast.Continue))
                       for s in node.body):
                continue  # handler does SOMETHING with the failure
            if self._justified(ctx, node):
                continue
            yield self.finding(
                ctx, node,
                "except Exception with a pass-only body silently "
                "swallows every failure here; log it, record it "
                "(counter / reply field / unreachable list), or state "
                "the reason swallowing is safe in the comment "
                "(`# noqa: BLE001 - <why>`)")


class UnboundedWaitInServingPath(Rule):
    id = "RT017"
    name = "unbounded-wait-in-serving-path"
    rationale = ("blocking ray_tpu.get()/wait() without an explicit "
                 "finite timeout in request-serving paths (serve/, "
                 "dashboard/) turns overload into hangs: one stuck "
                 "replica or store pull parks a proxy/handler thread "
                 "forever, and a saturated thread pool collapses "
                 "instead of shedding load")

    # Directories whose code sits on a request-serving path: every
    # thread there is a bounded resource a client is waiting on.
    _SERVING_DIR_PARTS = frozenset({"serve", "dashboard"})

    def _serving(self, path: str) -> bool:
        # DIRECTORY parts only — tools/bench_serve.py is a harness, not
        # a serving path; its basename merely contains "serve"
        parts = [p for p in re.split(r"[\\/]", path) if p][:-1]
        return bool(set(parts) & self._SERVING_DIR_PARTS)

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not self._serving(ctx.path):
            return
        for call in _blocking_calls(ctx):
            fn = ctx.call_name(call)
            timeout = next((k.value for k in call.keywords
                            if k.arg == "timeout"), None)
            unbounded = timeout is None or (
                isinstance(timeout, ast.Constant)
                and timeout.value is None)
            if unbounded:
                yield self.finding(
                    ctx, call,
                    f"{fn}() on a request-serving path without an "
                    f"explicit finite timeout= waits forever when a "
                    f"replica/store wedges — bound it (e.g. "
                    f"Config.serve_request_timeout_s) so overload "
                    f"sheds instead of hanging")


class OwnershipBookkeepingDiscipline(Rule):
    id = "RT018"
    name = "ownership-bookkeeping-discipline"
    rationale = ("the ownership protocol's count dicts (refcounts, pins, "
                 "borrower registrations, reader leases, lease "
                 "slots/parked/pipeline accounting) are state machines "
                 "whose invariants live in _private/ownership.py — a "
                 "direct mutation elsewhere bypasses the transition() "
                 "choke point, so double-releases and negative counts "
                 "corrupt silently instead of raising, and the "
                 "transition ring no longer explains the object")

    # Attribute names that ARE ownership-protocol state wherever they
    # appear in the framework (chosen to be distinctive; `leases` and
    # `pinned` exist only on protocol objects here).
    PROTECTED = frozenset({
        "local_refs", "arg_pins", "borrower_pins", "borrowed",
        "replica_leases", "_replica_leases", "nested_borrows",
        "_nested_borrows", "ttl_pins", "_ttl_pins", "_lease_running",
        "lease_inflight", "requests_in_flight", "parked_at", "leases",
        "pinned",
    })

    _MUTATORS = frozenset({
        "pop", "popitem", "setdefault", "clear", "update", "append",
        "appendleft", "extend", "remove", "discard", "add", "insert",
        "popleft",
    })

    _EXEMPT_SUFFIX = ("_private/ownership.py", "_private\\ownership.py")

    def _protected_attr(self, node: ast.AST) -> Optional[str]:
        """The protected attribute a mutation target reaches, if any:
        `x.arg_pins` itself or `x.arg_pins[...]`."""
        if isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Attribute) and node.attr in self.PROTECTED:
            return node.attr
        return None

    def _msg(self, attr: str, how: str) -> str:
        return (f"direct {how} of ownership-protocol state `{attr}` "
                f"outside _private/ownership.py bypasses the "
                f"transition() choke point — route it through the "
                f"RefTable/LeaseTable/store-ledger methods (or suppress "
                f"with `# graftlint: disable=RT018` if this attribute "
                f"is not protocol state)")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.path.replace("\\", "/").endswith(
                self._EXEMPT_SUFFIX[0]):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    attr = self._protected_attr(tgt)
                    if attr is None:
                        continue
                    if isinstance(tgt, ast.Attribute):
                        # plain rebinding: aliasing another component's
                        # table (`self.arg_pins = self._own.arg_pins`)
                        # and constructing a ledger from the ownership
                        # module are the two legitimate forms
                        if isinstance(node.value, ast.Attribute):
                            continue
                        if isinstance(node.value, ast.Call):
                            fname = ctx.call_name(node.value) or ""
                            if "ownership" in fname:
                                continue
                    yield self.finding(ctx, node,
                                       self._msg(attr, "assignment"))
            elif isinstance(node, ast.AugAssign):
                attr = self._protected_attr(node.target)
                if attr is not None:
                    yield self.finding(
                        ctx, node, self._msg(attr, "augmented assignment"))
            elif isinstance(node, ast.Delete):
                for tgt in node.targets:
                    attr = self._protected_attr(tgt)
                    if attr is not None:
                        yield self.finding(ctx, node,
                                           self._msg(attr, "delete"))
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in self._MUTATORS:
                attr = self._protected_attr(node.func.value)
                if attr is not None:
                    yield self.finding(
                        ctx, node,
                        self._msg(attr, f"`.{node.func.attr}()` call"))


class BlockingCallInAsync(Rule):
    id = "RT019"
    name = "blocking-call-in-async"
    rationale = ("a blocking call (time.sleep, ray_tpu.get/wait, raw "
                 "socket/file/subprocess ops) directly inside an "
                 "`async def` body stalls the whole event loop: every "
                 "other coroutine on that loop — every other request "
                 "on an ingress proxy — freezes for the call's "
                 "duration; bridge through run_in_executor or the "
                 "done-callback bridge (proxy_fleet/async_bridge.py) "
                 "instead")

    # beyond the shared blocking registry: calls that read files or
    # hit the network synchronously (the "raw file read" class)
    _EXTRA_DOTTED = frozenset({
        "open", "urllib.request.urlopen", "requests.get",
        "requests.post", "requests.put", "requests.delete",
        "socket.socket", "socket.getaddrinfo",
    })

    def _nearest_fn(self, ctx: ModuleContext, node: ast.AST):
        fns = ctx.enclosing_functions(node)
        return fns[0] if fns else None

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        from ray_tpu.lint.concurrency import match_blocking_call
        async_fns = [n for n in ast.walk(ctx.tree)
                     if isinstance(n, ast.AsyncFunctionDef)]
        for fn in async_fns:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                # only calls whose NEAREST enclosing function is this
                # async def: a sync closure/lambda shipped to
                # run_in_executor is the bridge pattern, not a finding
                if self._nearest_fn(ctx, node) is not fn:
                    continue
                # a call under an `await` expression is (part of) an
                # async call chain — asyncio.Event.wait(),
                # asyncio.wait_for(x.wait(), t) — not a thread block
                if any(isinstance(a, ast.Await)
                       for a in ctx.ancestors(node)):
                    continue
                desc = match_blocking_call(ctx, node)
                if desc is None:
                    dotted = ctx.call_name(node)
                    if dotted in self._EXTRA_DOTTED:
                        desc = f"{dotted}()"
                if desc is None:
                    continue
                yield self.finding(
                    ctx, node,
                    f"blocking {desc} inside async def "
                    f"'{fn.name}' stalls the event loop (and every "
                    f"request riding it) — await an async "
                    f"equivalent, run_in_executor, or the "
                    f"done-callback bridge")


# Concurrency layer (class-level guard maps + lock-order graph) lives
# in its own module; the rules plug into the same catalogue.
from ray_tpu.lint.concurrency import (BlockingUnderLock,  # noqa: E402
                                      LockOrderCycle, MixedGuardAccess)
# JAX/XLA hot-path layer (recompile hazards, hidden syncs, donation,
# leak-on-raise, unattributed sleeps) — the static half of the
# jax_sentinel / goodput-ledger pairing.
from ray_tpu.lint.jaxrules import (DonationMisuse,  # noqa: E402
                                   HiddenHostSync, LeakOnRaise,
                                   RecompileHazard, UnattributedSleep)

ALL_RULES: List[Rule] = [
    NestedBlockingGet(), GetInLoop(), HostEffectInJit(),
    ClosureMutationInJit(), ActorCallWithoutRemote(), LeakedObjectRef(),
    DictOrderPytree(), SwallowedException(), StoreViewCopy(),
    WallClockDuration(), MetricNameConvention(), BarePrintInFramework(),
    SilentExceptionSwallow(), MixedGuardAccess(), BlockingUnderLock(),
    LockOrderCycle(), UnboundedWaitInServingPath(),
    OwnershipBookkeepingDiscipline(), BlockingCallInAsync(),
    RecompileHazard(), HiddenHostSync(), DonationMisuse(),
    LeakOnRaise(), UnattributedSleep(),
]

RULES_BY_ID = {r.id: r for r in ALL_RULES}
